"""Fused star-schema join chains (exec/joins/chain.py): fused vs
per-operator fallback differential, build reuse on fallback, dense guard.

Oracle: pandas merges over the same frames.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.joins import BroadcastHashJoinExec
from auron_tpu.exec.joins import chain as chain_mod
from auron_tpu.exec.joins.driver import EquiJoinDriver
from auron_tpu.exprs.ir import col


def _mk(df, chunk=None):
    if chunk is None:
        return MemoryScanExec.single(
            [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]
        )
    bs = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + chunk], preserve_index=False)
        )
        for i in range(0, len(df), chunk)
    ]
    return MemoryScanExec.single(bs)


def _star(fact, dims, dim_keys, unique=True):
    """fact JOIN dim0 ON fact.k0 = dim0.id JOIN dim1 ON fact.k1 = dim1.id ..."""
    node = _mk(fact, chunk=37)
    nleft = len(fact.columns)
    for i, (dim, fk) in enumerate(zip(dims, dim_keys)):
        node = BroadcastHashJoinExec(
            node, _mk(dim), [col(fk)], [col(0)], "inner", build_side="right"
        )
        nleft += len(dim.columns)
    return node


def _oracle(fact, dims, dim_key_names):
    out = fact
    for dim, k in zip(dims, dim_key_names):
        out = out.merge(dim, left_on=k, right_on=dim.columns[0], how="inner")
    return out


def _collect_sorted(op):
    got = op.collect_pydict()
    df = pd.DataFrame(got)
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _fact_dims(n=500, nd1=40, nd2=25, seed=0):
    rng = np.random.default_rng(seed)
    fact = pd.DataFrame({
        "k0": rng.integers(0, nd1 + 5, n),  # some keys miss (5 dangling ids)
        "k1": rng.integers(0, nd2 + 5, n),
        "amt": rng.normal(size=n).round(3),
    })
    d1 = pd.DataFrame({"id1": np.arange(nd1), "d1v": np.arange(nd1) * 10})
    d2 = pd.DataFrame({"id2": np.arange(nd2), "d2v": np.arange(nd2) * 7})
    return fact, d1, d2


def test_fused_two_level_chain_matches_oracle():
    fact, d1, d2 = _fact_dims()
    top = _star(fact, [d1, d2], [0, 1])
    calls = {"fused": 0}
    orig = chain_mod._run_chain

    def spy(*a, **k):
        calls["fused"] += 1
        return orig(*a, **k)

    chain_mod._run_chain, saved = spy, orig
    try:
        got = _collect_sorted(top)
    finally:
        chain_mod._run_chain = saved
    assert calls["fused"] == 1, "fused path must engage for a unique star chain"
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_non_unique_build_falls_back_without_rebuilding():
    fact, d1, d2 = _fact_dims(n=300)
    # duplicate a dim row: build no longer unique -> fusion must fall back
    d2_dup = pd.concat([d2, d2.iloc[[3]]], ignore_index=True)
    top = _star(fact, [d1, d2_dup], [0, 1])

    prepares = {"n": 0}
    orig_prepare = EquiJoinDriver.prepare

    def counting_prepare(self, batches, conf=None):
        prepares["n"] += 1
        return orig_prepare(self, batches, conf=conf)

    EquiJoinDriver.prepare = counting_prepare
    try:
        got = _collect_sorted(top)
    finally:
        EquiJoinDriver.prepare = orig_prepare
    # 2 joins -> exactly 2 builds even though fusion was attempted and
    # abandoned (the memo hands the prepared maps to the fallback path)
    assert prepares["n"] == 2, f"builds ran {prepares['n']} times, expected 2"
    exp = _oracle(fact, [d1, d2_dup], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_dense_survival_chain_matches_oracle():
    # every fact row matches every dim -> n_live == capacity -> dense path
    rng = np.random.default_rng(1)
    n = 256
    fact = pd.DataFrame({
        "k0": rng.integers(0, 8, n),
        "k1": rng.integers(0, 4, n),
        "amt": np.arange(n),
    })
    d1 = pd.DataFrame({"id1": np.arange(8), "d1v": np.arange(8) * 10})
    d2 = pd.DataFrame({"id2": np.arange(4), "d2v": np.arange(4) * 7})
    top = _star(fact, [d1, d2], [0, 1])
    got = _collect_sorted(top)
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_dense_accelerator_mode_no_sync():
    # compact off (accelerator default): the chain must still fuse, emitting
    # dense outputs with no host sync
    from auron_tpu.utils.config import JOIN_COMPACT_OUTPUT, active_conf

    fact, d1, d2 = _fact_dims(n=300, seed=7)
    top = _star(fact, [d1, d2], [0, 1])
    calls = {"fused": 0}
    orig = chain_mod._run_chain

    def spy(*a, **k):
        calls["fused"] += 1
        return orig(*a, **k)

    conf = active_conf()
    saved_mode = conf.get(JOIN_COMPACT_OUTPUT)
    conf.set(JOIN_COMPACT_OUTPUT, "off")
    chain_mod._run_chain = spy
    try:
        got = _collect_sorted(top)
    finally:
        chain_mod._run_chain = orig
        conf.set(JOIN_COMPACT_OUTPUT, saved_mode)
    assert calls["fused"] == 1
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_three_level_chain_with_nulls():
    rng = np.random.default_rng(2)
    n = 400
    fact = pd.DataFrame({
        "k0": pd.array(
            [None if i % 11 == 0 else int(rng.integers(0, 20)) for i in range(n)],
            dtype="Int64",
        ),
        "k1": rng.integers(0, 15, n),
        "k2": rng.integers(0, 10, n),
        "amt": rng.normal(size=n).round(3),
    })
    d1 = pd.DataFrame({"id1": np.arange(20), "d1v": np.arange(20) * 10})
    d2 = pd.DataFrame({"id2": np.arange(15), "d2v": np.arange(15) * 7})
    d3 = pd.DataFrame({"id3": np.arange(10), "d3v": np.arange(10) * 3})
    top = _star(fact, [d1, d2, d3], [0, 1, 2])
    got = _collect_sorted(top)
    exp = fact.dropna(subset=["k0"]).astype({"k0": "int64"})
    exp = _oracle(exp, [d1, d2, d3], ["k0", "k1", "k2"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


# ---------------------------------------------------------------------------
# sync-free predicted compaction (exec/selectivity.py + runtime/transfer.py)
# ---------------------------------------------------------------------------


from auron_tpu.exec.selectivity import SelectivityPredictor as _RealPredictor


class _SpyPredictor:
    """Wraps SelectivityPredictor construction so tests can assert the
    predicted path (and its mispredict/repair protocol) actually ran."""

    instances: list = []

    def __new__(cls, conf=None):
        p = _RealPredictor(conf)
        cls.instances.append(p)
        return p


def _with_spy(monkeypatch):
    import auron_tpu.exec.selectivity as sel_mod

    _SpyPredictor.instances = []
    monkeypatch.setattr(chain_mod, "SelectivityPredictor", _SpyPredictor)
    monkeypatch.setattr(sel_mod, "SelectivityPredictor", _SpyPredictor)
    return _SpyPredictor


def _run_both_modes(top_builder):
    """Collect with predictor on (default) vs off (blocking per-batch
    sync) — the two must produce identical row sets."""
    from auron_tpu.utils.config import (
        JOIN_COMPACT_OUTPUT, SELECTIVITY_PREDICTOR_ENABLE, active_conf,
    )

    conf = active_conf()
    saved_c = conf.get(JOIN_COMPACT_OUTPUT)
    saved_p = conf.get(SELECTIVITY_PREDICTOR_ENABLE)
    conf.set(JOIN_COMPACT_OUTPUT, "on")
    try:
        conf.set(SELECTIVITY_PREDICTOR_ENABLE, "on")
        got_pred = _collect_sorted(top_builder())
        conf.set(SELECTIVITY_PREDICTOR_ENABLE, "off")
        got_sync = _collect_sorted(top_builder())
    finally:
        conf.set(JOIN_COMPACT_OUTPUT, saved_c)
        conf.set(SELECTIVITY_PREDICTOR_ENABLE, saved_p)
    return got_pred, got_sync


def test_chain_predictor_forced_mispredict_repair(monkeypatch):
    """Selectivity jumps from ~0 to ~100% mid-stream: the predicted bucket
    is far too small, the repair path must re-emit and the results stay
    bit-identical to the blocking mode AND the pandas oracle."""
    spy = _with_spy(monkeypatch)
    n = 6000
    # chunk 0 (1000 rows, capacity 1024): almost nothing survives (seeds a
    # tiny bucket, and compaction pays at cap 1024); later chunks: every
    # row survives -> guaranteed bucket-too-small repair
    k0 = np.where(np.arange(n) < 1000, 999, np.arange(n) % 8)
    fact = pd.DataFrame({"k0": k0, "k1": np.arange(n) % 4, "amt": np.arange(n)})
    d1 = pd.DataFrame({"id1": np.arange(8), "d1v": np.arange(8) * 10})
    d2 = pd.DataFrame({"id2": np.arange(4), "d2v": np.arange(4) * 7})

    def build():
        node = _mk(fact, chunk=1000)
        for dim, fk in [(d1, 0), (d2, 1)]:
            node = BroadcastHashJoinExec(
                node, _mk(dim), [col(fk)], [col(0)], "inner",
                build_side="right",
            )
        return node

    got_pred, got_sync = _run_both_modes(build)
    pd.testing.assert_frame_equal(got_pred, got_sync, check_dtype=False)
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got_pred.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got_pred, exp, check_dtype=False)
    assert any(p.mispredicts > 0 for p in spy.instances), \
        "selectivity jump must exercise the bucket-too-small repair path"
    assert any(p.predictions > 0 for p in spy.instances)


def test_chain_predictor_parity_fuzz(monkeypatch):
    """Randomized selectivity patterns: predictor-compacted vs blocking
    output row sets are identical (and match pandas) across seeds."""
    spy = _with_spy(monkeypatch)
    for seed in range(4):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(800, 4000))
        nd1 = int(rng.integers(4, 60))
        nd2 = int(rng.integers(4, 40))
        # per-chunk selectivity regime shifts (chunk size 257 is coprime
        # with the regime length so bucket demand keeps moving)
        regime = rng.integers(1, 4, size=n)
        hi = nd1 + int(rng.integers(1, 30))
        k0 = np.where(regime == 1, rng.integers(0, max(nd1 // 4, 1), n),
             np.where(regime == 2, rng.integers(0, hi, n),
                      rng.integers(nd1, hi, n)))
        fact = pd.DataFrame({
            "k0": k0,
            "k1": rng.integers(0, nd2 + 3, n),
            "amt": rng.normal(size=n).round(3),
        })
        d1 = pd.DataFrame({"id1": np.arange(nd1), "d1v": np.arange(nd1) * 10})
        d2 = pd.DataFrame({"id2": np.arange(nd2), "d2v": np.arange(nd2) * 7})

        def build():
            node = _mk(fact, chunk=257)
            for dim, fk in [(d1, 0), (d2, 1)]:
                node = BroadcastHashJoinExec(
                    node, _mk(dim), [col(fk)], [col(0)], "inner",
                    build_side="right",
                )
            return node

        got_pred, got_sync = _run_both_modes(build)
        pd.testing.assert_frame_equal(got_pred, got_sync, check_dtype=False)
        exp = _oracle(fact, [d1, d2], ["k0", "k1"])
        exp.columns = got_pred.columns
        exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
        pd.testing.assert_frame_equal(got_pred, exp, check_dtype=False)
    assert any(p.predictions > 0 for p in spy.instances)


def test_bhj_driver_predictor_parity_with_mispredict(monkeypatch):
    """Single unique-build BHJ (driver._emit_unique_compacted path): the
    pipelined predicted compaction must match the blocking mode and the
    oracle, including a forced bucket-too-small repair."""
    spy = _with_spy(monkeypatch)
    n = 6000
    # chunk 0 (capacity 1024) nearly empty output; later chunks ~full
    k0 = np.where(np.arange(n) < 1000, 99999, np.arange(n) % 16)
    fact = pd.DataFrame({"k0": k0, "amt": np.arange(n) * 1.5})
    d1 = pd.DataFrame({"id1": np.arange(16), "d1v": np.arange(16) * 10})

    def build():
        return BroadcastHashJoinExec(
            _mk(fact, chunk=1000), _mk(d1), [col(0)], [col(0)], "inner",
            build_side="right",
        )

    got_pred, got_sync = _run_both_modes(build)
    pd.testing.assert_frame_equal(got_pred, got_sync, check_dtype=False)
    exp = _oracle(fact, [d1], ["k0"])
    exp.columns = got_pred.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got_pred, exp, check_dtype=False)
    assert any(p.mispredicts > 0 for p in spy.instances)


def test_chain_window_depth_one_matches(monkeypatch):
    """Window depth 1 (classic one-deep pipeline) stays correct."""
    from auron_tpu.utils.config import TRANSFER_WINDOW_DEPTH, active_conf

    conf = active_conf()
    saved = conf.get(TRANSFER_WINDOW_DEPTH)
    conf.set(TRANSFER_WINDOW_DEPTH, 1)
    try:
        fact, d1, d2 = _fact_dims(n=700, seed=5)
        got = _collect_sorted(_star(fact, [d1, d2], [0, 1]))
    finally:
        conf.set(TRANSFER_WINDOW_DEPTH, saved)
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)

"""Fused star-schema join chains (exec/joins/chain.py): fused vs
per-operator fallback differential, build reuse on fallback, dense guard.

Oracle: pandas merges over the same frames.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu.columnar import Batch
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.joins import BroadcastHashJoinExec
from auron_tpu.exec.joins import chain as chain_mod
from auron_tpu.exec.joins.driver import EquiJoinDriver
from auron_tpu.exprs.ir import col


def _mk(df, chunk=None):
    if chunk is None:
        return MemoryScanExec.single(
            [Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))]
        )
    bs = [
        Batch.from_arrow(
            pa.RecordBatch.from_pandas(df.iloc[i : i + chunk], preserve_index=False)
        )
        for i in range(0, len(df), chunk)
    ]
    return MemoryScanExec.single(bs)


def _star(fact, dims, dim_keys, unique=True):
    """fact JOIN dim0 ON fact.k0 = dim0.id JOIN dim1 ON fact.k1 = dim1.id ..."""
    node = _mk(fact, chunk=37)
    nleft = len(fact.columns)
    for i, (dim, fk) in enumerate(zip(dims, dim_keys)):
        node = BroadcastHashJoinExec(
            node, _mk(dim), [col(fk)], [col(0)], "inner", build_side="right"
        )
        nleft += len(dim.columns)
    return node


def _oracle(fact, dims, dim_key_names):
    out = fact
    for dim, k in zip(dims, dim_key_names):
        out = out.merge(dim, left_on=k, right_on=dim.columns[0], how="inner")
    return out


def _collect_sorted(op):
    got = op.collect_pydict()
    df = pd.DataFrame(got)
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _fact_dims(n=500, nd1=40, nd2=25, seed=0):
    rng = np.random.default_rng(seed)
    fact = pd.DataFrame({
        "k0": rng.integers(0, nd1 + 5, n),  # some keys miss (5 dangling ids)
        "k1": rng.integers(0, nd2 + 5, n),
        "amt": rng.normal(size=n).round(3),
    })
    d1 = pd.DataFrame({"id1": np.arange(nd1), "d1v": np.arange(nd1) * 10})
    d2 = pd.DataFrame({"id2": np.arange(nd2), "d2v": np.arange(nd2) * 7})
    return fact, d1, d2


def test_fused_two_level_chain_matches_oracle():
    fact, d1, d2 = _fact_dims()
    top = _star(fact, [d1, d2], [0, 1])
    calls = {"fused": 0}
    orig = chain_mod._run_chain

    def spy(*a, **k):
        calls["fused"] += 1
        return orig(*a, **k)

    chain_mod._run_chain, saved = spy, orig
    try:
        got = _collect_sorted(top)
    finally:
        chain_mod._run_chain = saved
    assert calls["fused"] == 1, "fused path must engage for a unique star chain"
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_non_unique_build_falls_back_without_rebuilding():
    fact, d1, d2 = _fact_dims(n=300)
    # duplicate a dim row: build no longer unique -> fusion must fall back
    d2_dup = pd.concat([d2, d2.iloc[[3]]], ignore_index=True)
    top = _star(fact, [d1, d2_dup], [0, 1])

    prepares = {"n": 0}
    orig_prepare = EquiJoinDriver.prepare

    def counting_prepare(self, batches):
        prepares["n"] += 1
        return orig_prepare(self, batches)

    EquiJoinDriver.prepare = counting_prepare
    try:
        got = _collect_sorted(top)
    finally:
        EquiJoinDriver.prepare = orig_prepare
    # 2 joins -> exactly 2 builds even though fusion was attempted and
    # abandoned (the memo hands the prepared maps to the fallback path)
    assert prepares["n"] == 2, f"builds ran {prepares['n']} times, expected 2"
    exp = _oracle(fact, [d1, d2_dup], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_dense_survival_chain_matches_oracle():
    # every fact row matches every dim -> n_live == capacity -> dense path
    rng = np.random.default_rng(1)
    n = 256
    fact = pd.DataFrame({
        "k0": rng.integers(0, 8, n),
        "k1": rng.integers(0, 4, n),
        "amt": np.arange(n),
    })
    d1 = pd.DataFrame({"id1": np.arange(8), "d1v": np.arange(8) * 10})
    d2 = pd.DataFrame({"id2": np.arange(4), "d2v": np.arange(4) * 7})
    top = _star(fact, [d1, d2], [0, 1])
    got = _collect_sorted(top)
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_dense_accelerator_mode_no_sync():
    # compact off (accelerator default): the chain must still fuse, emitting
    # dense outputs with no host sync
    from auron_tpu.utils.config import JOIN_COMPACT_OUTPUT, active_conf

    fact, d1, d2 = _fact_dims(n=300, seed=7)
    top = _star(fact, [d1, d2], [0, 1])
    calls = {"fused": 0}
    orig = chain_mod._run_chain

    def spy(*a, **k):
        calls["fused"] += 1
        return orig(*a, **k)

    conf = active_conf()
    saved_mode = conf.get(JOIN_COMPACT_OUTPUT)
    conf.set(JOIN_COMPACT_OUTPUT, "off")
    chain_mod._run_chain = spy
    try:
        got = _collect_sorted(top)
    finally:
        chain_mod._run_chain = orig
        conf.set(JOIN_COMPACT_OUTPUT, saved_mode)
    assert calls["fused"] == 1
    exp = _oracle(fact, [d1, d2], ["k0", "k1"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_three_level_chain_with_nulls():
    rng = np.random.default_rng(2)
    n = 400
    fact = pd.DataFrame({
        "k0": pd.array(
            [None if i % 11 == 0 else int(rng.integers(0, 20)) for i in range(n)],
            dtype="Int64",
        ),
        "k1": rng.integers(0, 15, n),
        "k2": rng.integers(0, 10, n),
        "amt": rng.normal(size=n).round(3),
    })
    d1 = pd.DataFrame({"id1": np.arange(20), "d1v": np.arange(20) * 10})
    d2 = pd.DataFrame({"id2": np.arange(15), "d2v": np.arange(15) * 7})
    d3 = pd.DataFrame({"id3": np.arange(10), "d3v": np.arange(10) * 3})
    top = _star(fact, [d1, d2, d3], [0, 1, 2])
    got = _collect_sorted(top)
    exp = fact.dropna(subset=["k0"]).astype({"k0": "int64"})
    exp = _oracle(exp, [d1, d2, d3], ["k0", "k1", "k2"])
    exp.columns = got.columns
    exp = exp.sort_values(list(exp.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)

"""Shuffle block format v2: round-trip fuzz, chooser determinism,
corruption loudness, codec degradation, bucket-decode reader equality
(docs/shuffle.md)."""

import decimal
import io

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.basic import MemoryScanExec
from auron_tpu.exec.shuffle import HashPartitioning, IpcReaderExec, ShuffleWriterExec
from auron_tpu.exec.shuffle import format as F
from auron_tpu.exec.shuffle.reader import LocalFileBlockProvider
from auron_tpu.exec.shuffle.writer import encode_shuffle_block
from auron_tpu.exprs.ir import col
from auron_tpu.utils.config import (
    SHUFFLE_ENCODING,
    SPILL_COMPRESSION_CODEC,
    Configuration,
)

RNG = np.random.default_rng(7)


def _null_mask(n: int, pattern: str):
    if pattern == "none" or n == 0:
        return None
    if pattern == "all":
        return np.ones(n, dtype=bool)  # True = null (pa mask convention)
    m = RNG.random(n) < 0.3
    if not m.any():
        m[0] = True
    return m


def _column(kind: str, n: int, pattern: str) -> pa.Array:
    mask = _null_mask(n, pattern)
    if kind == "int64":
        vals = RNG.integers(-(10**12), 10**12, n)
        return pa.array(vals, mask=mask)
    if kind == "int_small":
        return pa.array(RNG.integers(0, 200, n).astype(np.int64), mask=mask)
    if kind == "int_runs":
        return pa.array(np.sort(RNG.integers(0, max(n // 50, 1), n)), mask=mask)
    if kind == "int32":
        return pa.array(RNG.integers(-1000, 1000, n).astype(np.int32), mask=mask)
    if kind == "int8":
        return pa.array(RNG.integers(-100, 100, n).astype(np.int8), mask=mask)
    if kind == "bool":
        return pa.array(RNG.random(n) < 0.5, mask=mask)
    if kind == "float64_dec":
        return pa.array(np.round(RNG.random(n) * 500, 2), mask=mask)
    if kind == "float64_rand":
        return pa.array(RNG.random(n), mask=mask)
    if kind == "float64_edge":
        base = np.where(RNG.random(n) < 0.5, -0.0, np.nan)
        base[::3] = 1.25
        return pa.array(base, mask=mask)
    if kind == "float32":
        return pa.array(
            np.round(RNG.random(n) * 9, 1).astype(np.float32), mask=mask)
    if kind == "ts":
        vals = RNG.integers(0, 10**15, n).astype("datetime64[us]")
        return pa.array(vals, mask=mask)
    if kind == "date32":
        return pa.array(
            RNG.integers(0, 20000, n).astype(np.int32), mask=mask
        ).cast(pa.date32())
    if kind == "decimal":
        pv = [decimal.Decimal(int(v)).scaleb(-2) for v in
              RNG.integers(-(10**6), 10**6, n)]
        arr = pa.array(pv, type=pa.decimal128(12, 2))
        if mask is not None:
            arr = pa.array(
                [None if m else v for v, m in zip(pv, mask)],
                type=pa.decimal128(12, 2))
        return arr
    if kind == "decimal_wide":
        # wide-decimal (p>18): the limbs genuinely use the high int64
        pv = [decimal.Decimal(int(v)) * (10**15) for v in
              RNG.integers(-(10**6), 10**6, n)]
        arr = pa.array([None if (mask is not None and m) else v
                        for v, m in zip(pv, mask if mask is not None else
                                        [False] * n)],
                       type=pa.decimal128(38, 0))
        return arr
    if kind == "dict_str":
        vals = RNG.choice(["alpha", "beta", "gamma", "delta"], n)
        arr = pa.array(vals, mask=mask)
        return arr.dictionary_encode()
    if kind == "str":
        vals = [f"s{int(v)}" for v in RNG.integers(0, 50, n)]
        if mask is not None:
            vals = [None if m else v for v, m in zip(vals, mask)]
        return pa.array(vals)
    raise AssertionError(kind)


KINDS = ["int64", "int_small", "int_runs", "int32", "int8", "bool",
         "float64_dec", "float64_rand", "float64_edge", "float32", "ts",
         "date32", "decimal", "decimal_wide", "dict_str", "str"]


def _assert_tables_bit_equal(t1: pa.Table, t2: pa.Table, ctx=""):
    """Column-wise byte-exact comparison. Arrow's Table.equals treats
    NaN != NaN, so float columns compare validity + BIT PATTERNS instead
    (stricter: -0.0 != 0.0, NaN payloads must survive)."""
    assert t1.schema.equals(t2.schema), ctx
    for i, f in enumerate(t1.schema):
        c1 = t1.column(i).combine_chunks()
        c2 = t2.column(i).combine_chunks()
        if pa.types.is_floating(f.type):
            import pyarrow.compute as pc

            v1 = pc.is_valid(c1).to_numpy(zero_copy_only=False)
            v2_ = pc.is_valid(c2).to_numpy(zero_copy_only=False)
            assert np.array_equal(v1, v2_), (ctx, f.name)
            u = np.uint64 if f.type == pa.float64() else np.uint32
            b1 = c1.fill_null(0).to_numpy(zero_copy_only=False).view(u)
            b2 = c2.fill_null(0).to_numpy(zero_copy_only=False).view(u)
            assert np.array_equal(b1[v1], b2[v1]), (ctx, f.name)
        else:
            assert c1.equals(c2), (ctx, f.name)


@pytest.mark.parametrize("n", [0, 1, 977])
@pytest.mark.parametrize("pattern", ["none", "some", "all"])
def test_v2_roundtrip_fuzz(n, pattern):
    """Every encoding x dtype x NULL pattern decodes byte-exactly to what
    the legacy zstd-IPC block yields for the same rows."""
    arrays = [_column(k, n, pattern) for k in KINDS]
    rb = pa.RecordBatch.from_arrays(arrays, names=KINDS)
    conf = Configuration().set(SPILL_COMPRESSION_CODEC, "zstd")
    legacy = list(F.decode_blocks(F.encode_block(rb, conf=conf)))
    v2 = list(F.decode_blocks(F.encode_block_v2([rb], conf=conf)))
    t_legacy = pa.Table.from_batches(legacy, schema=rb.schema)
    t_v2 = pa.Table.from_batches(v2, schema=rb.schema)
    _assert_tables_bit_equal(t_legacy, t_v2, f"{pattern}/{n}")
    # and both match the source rows
    _assert_tables_bit_equal(pa.Table.from_batches([rb]), t_v2, "src")


def test_v2_encode_deterministic():
    rb = pa.RecordBatch.from_arrays(
        [_column(k, 500, "some") for k in KINDS], names=KINDS)
    assert F.encode_block_v2([rb]) == F.encode_block_v2([rb])


def test_v2_multi_batch_block():
    rbs = [pa.RecordBatch.from_arrays(
        [_column("int_small", 100, "none"), _column("float64_dec", 100, "some")],
        names=["a", "b"]) for _ in range(3)]
    out = list(F.decode_blocks(F.encode_block_v2(rbs)))
    got = pa.Table.from_batches(out)
    want = pa.Table.from_batches(rbs).combine_chunks()
    assert got.equals(want)


def test_v2_scaled_edge_values_roundtrip():
    """-0.0, NaN, Inf and near-2^53 magnitudes must never decode to
    different bits (the scaled encoder must refuse them)."""
    vals = np.array([0.0, -0.0, np.nan, np.inf, -np.inf, 1.25, 2.0**53,
                     -(2.0**53), 123.456, 1e300, 5e-324])
    rb = pa.RecordBatch.from_arrays([pa.array(vals)], names=["v"])
    out = list(F.decode_blocks(F.encode_block_v2([rb])))[0]
    got = out.column(0).to_numpy(zero_copy_only=False)
    assert np.array_equal(got.view(np.uint64), vals.view(np.uint64)), got


def test_scaled_f32_wide_span_numpy_twin_matches_native():
    """Regression: a float32 plane whose scaled span needs >24 bits must
    round-trip exactly on BOTH the native kernel and the numpy fallback
    (the fallback once subtracted the FOR reference in float32, rounding
    16777217 offsets to 16777216 — silent corruption), and the two paths
    must emit identical bytes."""
    from auron_tpu import native

    vals = np.array([1.0, 16777218.0, 2.0, 33554436.0], dtype=np.float32)
    rb = pa.RecordBatch.from_arrays([pa.array(vals)], names=["v"])
    blk_native = F.encode_block_v2([rb])
    # force the numpy twin
    orig = native.scaled_probe_host
    try:
        native.scaled_probe_host = lambda a, s: False
        blk_numpy = F.encode_block_v2([rb])
    finally:
        native.scaled_probe_host = orig
    assert blk_native == blk_numpy
    out = list(F.decode_blocks(blk_numpy))[0].column(0).to_numpy(
        zero_copy_only=False)
    assert np.array_equal(out.view(np.uint32), vals.view(np.uint32))


def test_v2_corrupt_block_fails_loudly():
    rb = pa.RecordBatch.from_arrays(
        [pa.array(np.arange(100, dtype=np.int64))], names=["x"])
    blk = F.encode_block_v2([rb])
    payload = blk[8:]
    # truncated column payload
    with pytest.raises(ValueError):
        F.decode_block_v2(payload[: len(payload) // 2])
    # bad version
    bad = bytearray(payload)
    bad[4] = 9
    with pytest.raises(ValueError):
        F.decode_block_v2(bytes(bad))
    # framing overrun
    with pytest.raises(ValueError):
        list(F.iter_block_payloads(blk[:-4]))


class _UnavailableCodec:
    @staticmethod
    def is_available(name):
        return False


def test_unavailable_codec_degrades_with_one_warning(monkeypatch, capsys):
    """PR-5 importorskip treatment: a conf naming a codec the runtime
    lacks degrades to light-weight encodings + ONE stderr warning, never
    a failed write."""
    F._codec_warned.clear()
    monkeypatch.setattr(F.pa, "Codec", _UnavailableCodec)
    conf = Configuration().set("exec.shuffle.encoding.fallback.codec", "zstd")
    rb = pa.RecordBatch.from_arrays(
        [pa.array(RNG.random(5000))], names=["v"])  # incompressible floats
    blk = F.encode_block_v2([rb], conf=conf)
    blk2 = F.encode_block_v2([rb], conf=conf)
    err = capsys.readouterr().err
    assert err.count("unavailable") >= 1
    # warn once per codec name, not per block
    assert err.count("'zstd' unavailable") == 1
    out = list(F.decode_blocks(blk))[0]
    assert out.column(0).to_pylist() == rb.column(0).to_pylist()
    assert blk == blk2


def test_writer_off_mode_emits_v1_ipc_blocks(tmp_path):
    """exec.shuffle.encoding=off restores the legacy compressed-IPC block
    bytes exactly (the conf contract)."""
    df = pd.DataFrame({"k": np.arange(500) % 7, "v": np.arange(500.0)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    part = HashPartitioning([col(0)], 3)
    files = {}
    for mode in ("off", "on"):
        conf = Configuration().set(SHUFFLE_ENCODING, mode)
        data = str(tmp_path / f"{mode}.data")
        index = str(tmp_path / f"{mode}.index")
        w = ShuffleWriterExec(MemoryScanExec.single([b]), part, data, index)
        list(w.execute(0, ExecutionContext(partition_id=0, conf=conf)))
        files[mode] = (data, index)
    prov_off = LocalFileBlockProvider(*files["off"])
    prov_on = LocalFileBlockProvider(*files["on"])
    for p in range(3):
        for pay in prov_off.iter_payloads(p):
            assert not F.is_v2_payload(pay)
            with pa.ipc.open_stream(pay):  # genuinely v1
                pass
        for pay in prov_on.iter_payloads(p):
            assert F.is_v2_payload(pay)
    # same logical rows either way
    rows_off = sorted(
        r["v"] for p in range(3) for rb in prov_off(p) for r in rb.to_pylist())
    rows_on = sorted(
        r["v"] for p in range(3) for rb in prov_on(p) for r in rb.to_pylist())
    assert rows_off == rows_on == sorted(df["v"].tolist())


def _read_batches(schema, provider, n_parts, conf):
    out = []
    for p in range(n_parts):
        r = IpcReaderExec(schema, "blocks")
        ctx = ExecutionContext(partition_id=p, conf=conf)
        ctx.resources["blocks"] = provider
        out.extend(b.to_arrow() for b in r.execute(p, ctx))
    return out


@pytest.mark.parametrize("writer_mode", ["off", "on"])
def test_bucket_decode_matches_legacy_reader(tmp_path, writer_mode):
    """The reader's direct capacity-bucket decode yields the same rows as
    the legacy Arrow-table path, for BOTH block versions (mixed-region
    tolerance), including dict-encoded strings and decimals."""
    df = pd.DataFrame({
        "k": np.arange(2000) % 13,
        "price": np.round(RNG.random(2000) * 100, 2),
        "s": RNG.choice(["x", "y", "z"], 2000),
    })
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    part = HashPartitioning([col(0)], 4)
    conf_w = Configuration().set(SHUFFLE_ENCODING, writer_mode)
    data = str(tmp_path / "m.data")
    index = str(tmp_path / "m.index")
    w = ShuffleWriterExec(MemoryScanExec.single([b]), part, data, index)
    list(w.execute(0, ExecutionContext(partition_id=0, conf=conf_w)))
    prov = LocalFileBlockProvider(data, index)
    legacy = _read_batches(
        b.schema, prov, 4, Configuration().set(SHUFFLE_ENCODING, "off"))
    direct = _read_batches(
        b.schema, prov, 4, Configuration().set(SHUFFLE_ENCODING, "on"))
    key = lambda rows: sorted(
        (r["k"], r["price"], r["s"]) for r in rows)
    legacy_rows = key(r for rb in legacy for r in rb.to_pylist())
    direct_rows = key(r for rb in direct for r in rb.to_pylist())
    assert legacy_rows == direct_rows
    assert legacy_rows == key(df.to_dict("records"))


def test_bucket_decode_wide_decimal_and_nulls(tmp_path):
    pv = [None if i % 5 == 0 else decimal.Decimal(i) * (10**15)
          for i in range(600)]
    rb = pa.RecordBatch.from_arrays([
        pa.array(np.arange(600) % 3),
        pa.array(pv, type=pa.decimal128(38, 0)),
    ], names=["k", "d"])
    b = Batch.from_arrow(rb)
    part = HashPartitioning([col(0)], 2)
    data = str(tmp_path / "d.data")
    index = str(tmp_path / "d.index")
    w = ShuffleWriterExec(MemoryScanExec.single([b]), part, data, index)
    list(w.execute(0, ExecutionContext(partition_id=0)))
    prov = LocalFileBlockProvider(data, index)
    got = _read_batches(b.schema, prov, 2,
                        Configuration().set(SHUFFLE_ENCODING, "on"))
    vals = sorted(
        (r["d"] for rb_ in got for r in rb_.to_pylist() if r["d"] is not None))
    want = sorted(v for v in pv if v is not None)
    assert vals == want
    nulls = sum(1 for rb_ in got for r in rb_.to_pylist() if r["d"] is None)
    assert nulls == sum(1 for v in pv if v is None)


def test_encoding_histogram_metrics(tmp_path):
    df = pd.DataFrame({"k": np.arange(3000) % 5,
                       "price": np.round(RNG.random(3000) * 9, 2)})
    b = Batch.from_arrow(pa.RecordBatch.from_pandas(df, preserve_index=False))
    data = str(tmp_path / "h.data")
    index = str(tmp_path / "h.index")
    w = ShuffleWriterExec(
        MemoryScanExec.single([b]), HashPartitioning([col(0)], 2), data, index)
    ctx = ExecutionContext(partition_id=0)
    list(w.execute(0, ctx))
    hist = {
        k: v for k, v in
        ((m, ctx.metrics.total(f"shuffle_enc_{m}"))
         for m in F.ENC_NAMES.values()) if v
    }
    assert hist, "no encodings recorded"
    assert ctx.metrics.total("shuffle_bytes_raw") > 0
    assert ctx.metrics.total("shuffle_bytes_written") > 0

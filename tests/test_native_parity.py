"""Native <-> numpy twin parity, parametrized from auronlint R15.

The R15 FFI-lockstep rule (tools/auronlint/rules/ffilockstep.py) already
enumerates every exported kernel's (symbol, twin) pair while proving the
ctypes bindings; this suite closes the loop dynamically — for each pair
it drives the native kernel and the pure-numpy twin on identical inputs
and pins the outputs BYTE-identical. A kernel whose twin drifts (the
silent corruption class: the f32 FOR-offset rounding bug shape) fails
here instead of shipping two decoders that disagree.

The driver registry is keyed by exported symbol and the completeness
test fails when R15 learns a pair this suite has no driver for — adding
a kernel forces adding its parity case. Skips cleanly when the shared
library is absent: the twins ARE the engine then, and there is nothing
to compare.
"""

import contextlib
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from auron_tpu import native  # noqa: E402

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="libauron_native.so absent: numpy twins are the only impl",
)


def _r15_pairs():
    from tools.auronlint import REPO_ROOT
    from tools.auronlint.rules.ffilockstep import analyze

    _findings, stats = analyze(REPO_ROOT)
    return sorted(set(stats["pairs"]))


@contextlib.contextmanager
def _without_library(mp):
    """Force every *_host entry onto its numpy fallback path."""
    with mp.context() as m:
        m.setattr(native, "_LIB", None)
        m.setattr(native, "_TRIED", True)
        yield


def _drv_murmur3_i32(mp):
    v = np.array([0, 1, -1, 42, 2**31 - 1, -(2**31), 123456789, -7],
                 dtype=np.int32)
    nat = native.murmur3_i32_host(v, seed=42)
    with _without_library(mp):
        twin = native.murmur3_i32_host(v, seed=42)
    assert nat.tobytes() == twin.tobytes()


def _drv_murmur3_i64(mp):
    v = np.array([0, 1, -1, 2**63 - 1, -(2**63), 123456789, -7],
                 dtype=np.int64)
    nat = native.murmur3_i64_host(v, seed=42)
    with _without_library(mp):
        twin = native.murmur3_i64_host(v, seed=42)
    assert nat.tobytes() == twin.tobytes()


def _drv_murmur3_bytes(mp):
    strings = [b"hello", b"bar", b"", "\U0001f601".encode(),
               "天地".encode(), b"auron-tpu"]
    data = b"".join(strings)
    offsets = np.cumsum([0] + [len(s) for s in strings]).astype(np.int64)
    nat = native.murmur3_bytes_host(data, offsets, seed=42)
    with _without_library(mp):
        twin = native.murmur3_bytes_host(data, offsets, seed=42)
    assert nat.tobytes() == twin.tobytes()


def _drv_radix_partition(mp):
    pids = np.array([3, 0, 2, 1, 3, 3, 0, 2, 2, 1, 0, 3, 1, 1, 0],
                    dtype=np.int32)
    nat_counts, nat_order = native.radix_partition_host(pids, 4)
    with _without_library(mp):
        twin_counts, twin_order = native.radix_partition_host(pids, 4)
    assert nat_counts.tobytes() == twin_counts.tobytes()
    assert nat_order.tobytes() == twin_order.tobytes()


def _drv_loser_tree_merge(mp):
    # three sorted runs, two key words each; keys unique across runs so
    # parity does not hinge on tie-break conventions
    runs = [
        [np.array([0, 9, 18, 27], np.uint64), np.array([1, 2, 3, 4], np.uint64)],
        [np.array([1, 10, 19], np.uint64), np.array([5, 6, 7], np.uint64)],
        [np.array([2, 11, 20, 29, 38], np.uint64),
         np.array([8, 9, 10, 11, 12], np.uint64)],
    ]
    nat_run, nat_idx = native.loser_tree_merge_host(runs)
    with _without_library(mp):
        twin_run, twin_idx = native.loser_tree_merge_host(runs)
    assert nat_run.tobytes() == twin_run.tobytes()
    assert nat_idx.tobytes() == twin_idx.tobytes()


def _drv_crc32c_hash(mp):
    from auron_tpu.exec.kafka_wire import crc32c

    data = bytes(range(256)) * 3 + b"auron-tpu record batch"
    nat = native.crc32c_host(data, 0)
    assert nat is not None
    with _without_library(mp):
        twin = crc32c(data, 0)  # table-loop fallback
    assert nat == twin
    # RFC 3720 check vector pins the polynomial itself
    assert native.crc32c_host(b"123456789", 0) == 0xE3069283


def _scaled_plane(dtype):
    # decimal-in-float plane (k/10): the ENC_SCALED shape, e = 1
    k = np.arange(-1000, 1000, dtype=np.int64)
    return (k.astype(dtype) / dtype(10.0)).astype(dtype)


def _drv_scaled_probe(dtype, mp):
    a = _scaled_plane(dtype)
    s = 10.0
    probed = native.scaled_probe_host(a, s)
    assert probed not in (None, False)
    # twin simulation: the exact arithmetic _scaled_pack's numpy branch
    # uses (format.py) — native must agree on the verified range
    t = a * a.dtype.type(s)
    t = np.round(t)
    assert np.array_equal(t / a.dtype.type(s), a)
    assert probed == (int(t.min()), int(t.max()))
    # refusal parity: NaN and -0.0 planes must refuse on both sides
    bad = a.copy()
    bad[3] = np.nan
    assert native.scaled_probe_host(bad, s) is None
    neg0 = a.copy()
    neg0[5] = dtype(-0.0)
    assert native.scaled_probe_host(neg0, s) is None


def _drv_scaled_pack(dtype, mp):
    from auron_tpu.exec.shuffle import format as fmt

    a = _scaled_plane(dtype)
    nat = fmt._scaled_pack(a, 1)
    assert nat is not None
    with _without_library(mp):
        twin = fmt._scaled_pack(a, 1)
    assert twin is not None
    assert nat == twin


def _drv_scaled_unpack(dtype, mp):
    from auron_tpu.exec.shuffle import format as fmt

    a = _scaled_plane(dtype)
    payload = fmt._scaled_pack(a, 1)
    assert payload is not None
    nat = fmt._decode_float_plane(fmt.ENC_SCALED, payload, len(a),
                                  np.dtype(dtype))
    with _without_library(mp):
        twin = fmt._decode_float_plane(fmt.ENC_SCALED, payload, len(a),
                                       np.dtype(dtype))
    assert nat.tobytes() == twin.tobytes() == a.tobytes()


_DRIVERS = {
    "murmur3_i32": _drv_murmur3_i32,
    "murmur3_i64": _drv_murmur3_i64,
    "murmur3_bytes": _drv_murmur3_bytes,
    "radix_partition": _drv_radix_partition,
    "loser_tree_merge": _drv_loser_tree_merge,
    "crc32c_hash": _drv_crc32c_hash,
    "scaled_probe_f64": lambda mp: _drv_scaled_probe(np.float64, mp),
    "scaled_probe_f32": lambda mp: _drv_scaled_probe(np.float32, mp),
    "scaled_pack_f64": lambda mp: _drv_scaled_pack(np.float64, mp),
    "scaled_pack_f32": lambda mp: _drv_scaled_pack(np.float32, mp),
    "scaled_unpack_f64": lambda mp: _drv_scaled_unpack(np.float64, mp),
    "scaled_unpack_f32": lambda mp: _drv_scaled_unpack(np.float32, mp),
}

_PAIRS = _r15_pairs()


def test_every_r15_pair_has_a_parity_driver():
    """Teeth: a new exported kernel (R15 finds its twin) without a
    parity driver here fails the suite — coverage cannot rot silently."""
    assert {sym for sym, _twin in _PAIRS} == set(_DRIVERS)


@pytest.mark.parametrize(
    "sym,twin", _PAIRS, ids=[f"{s}~{t}" for s, t in _PAIRS]
)
def test_native_matches_numpy_twin(sym, twin, monkeypatch):
    _DRIVERS[sym](monkeypatch)

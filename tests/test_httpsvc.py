"""Observability HTTP service (auron/src/http/mod.rs analog)."""

import json
import urllib.request

import pytest

from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import col
from auron_tpu.plan import builders as B
from auron_tpu.utils import httpsvc


@pytest.fixture()
def svc():
    port = httpsvc.start(0)
    yield port
    httpsvc.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def test_healthz_and_conf(svc):
    code, body = _get(svc, "/healthz")
    assert code == 200 and body == "ok\n"
    code, body = _get(svc, "/conf")
    conf = json.loads(body)
    assert "exchange.mode" in conf and "batch.size" in conf


def test_metrics_expose_live_tasks(svc):
    b = Batch.from_pydict({"v": list(range(100))},
                          schema=T.Schema.of(T.Field("v", T.INT64)))
    api.put_resource("http_rows", [[b]])
    try:
        plan = B.hash_agg(B.memory_scan(b.schema, "http_rows"), [],
                          [("sum", col(0), "s")], "partial")
        h = api.call_native(B.task(plan).SerializeToString())
        # while the runtime is live, /metrics sees it
        code, body = _get(svc, "/metrics")
        payload = json.loads(body)
        assert code == 200
        assert str(h) in payload["tasks"]
        assert "budget_bytes" in payload["memory"]
        while api.next_batch(h) is not None:
            pass
        api.finalize_native(h)
    finally:
        api.remove_resource("http_rows")


def test_stacks_dump(svc):
    code, body = _get(svc, "/stacks")
    assert code == 200
    assert "--- thread" in body and "MainThread" in body


def test_conf_gated_autostart():
    from auron_tpu.utils.config import Configuration

    assert httpsvc.maybe_start_from_conf(Configuration()) is None  # off by default
    port = httpsvc.maybe_start_from_conf(
        Configuration().set(httpsvc.HTTP_SERVICE_ENABLE, True)
    )
    try:
        assert port is not None
        code, _ = _get(port, "/healthz")
        assert code == 200
    finally:
        httpsvc.stop()

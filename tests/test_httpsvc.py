"""Observability HTTP service (auron/src/http/mod.rs analog)."""

import json
import re
import urllib.error
import urllib.request

import pytest

from auron_tpu import types as T
from auron_tpu.bridge import api
from auron_tpu.columnar import Batch
from auron_tpu.exprs.ir import col
from auron_tpu.plan import builders as B
from auron_tpu.utils import httpsvc


@pytest.fixture()
def svc():
    port = httpsvc.start(0)
    yield port
    httpsvc.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def test_healthz_and_conf(svc):
    code, body = _get(svc, "/healthz")
    assert code == 200 and body == "ok\n"
    code, body = _get(svc, "/conf")
    conf = json.loads(body)
    assert "exchange.mode" in conf and "batch.size" in conf


def test_metrics_expose_live_tasks(svc):
    b = Batch.from_pydict({"v": list(range(100))},
                          schema=T.Schema.of(T.Field("v", T.INT64)))
    api.put_resource("http_rows", [[b]])
    try:
        plan = B.hash_agg(B.memory_scan(b.schema, "http_rows"), [],
                          [("sum", col(0), "s")], "partial")
        h = api.call_native(B.task(plan).SerializeToString())
        # while the runtime is live, /metrics sees it
        code, body = _get(svc, "/metrics")
        payload = json.loads(body)
        assert code == 200
        assert str(h) in payload["tasks"]
        assert "budget_bytes" in payload["memory"]
        while api.next_batch(h) is not None:
            pass
        api.finalize_native(h)
    finally:
        api.remove_resource("http_rows")


def test_stacks_dump(svc):
    code, body = _get(svc, "/stacks")
    assert code == 200
    assert "--- thread" in body and "MainThread" in body


# ---------------------------------------------------------------------------
# full endpoint sweep during a LIVE query (old and new endpoints)
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? -?[0-9.eE+-]+(?:nan|inf)?)$"
)


def _parse_prom(body: str) -> dict[str, list[str]]:
    """Validate Prometheus 0.0.4 text exposition; returns family->lines.
    Catches the two classic emitter pitfalls: a family declared twice
    (duplicate # TYPE blocks) and unescaped label values."""
    families: dict[str, list[str]] = {}
    declared: list[str] = []
    for ln in body.splitlines():
        if not ln.strip():
            continue
        assert _PROM_LINE.match(ln), f"bad exposition line: {ln!r}"
        if ln.startswith("# TYPE "):
            name = ln.split()[2]
            assert name not in declared, f"duplicate family {name}"
            declared.append(name)
        elif not ln.startswith("#"):
            name = ln.split("{")[0].split()[0]
            families.setdefault(name, []).append(ln)
    for name in families:
        assert name in declared, f"sample without TYPE: {name}"
    # series uniqueness within each family (duplicate-metric pitfall)
    for name, lines in families.items():
        series = [ln.rsplit(" ", 1)[0] for ln in lines]
        assert len(series) == len(set(series)), f"duplicate series in {name}"
    return families


def test_every_endpoint_during_live_query(svc):
    from auron_tpu import obs
    from auron_tpu.utils.profiling import EngineCounters

    EngineCounters.install()  # idempotent; /metrics.prom renders it
    b = Batch.from_pydict({"v": list(range(5000))},
                          schema=T.Schema.of(T.Field("v", T.INT64)))
    api.put_resource("http_live", [[b] * 4])
    try:
        with obs.query_trace("http_live_query") as qt:
            plan = B.hash_agg(B.memory_scan(b.schema, "http_live"), [],
                              [("sum", col(0), "s")], "partial")
            h = api.call_native(B.task(plan).SerializeToString())
            # hit EVERY endpoint while the task is live
            for path in ("/healthz", "/metrics", "/metrics.prom", "/stacks",
                         "/conf", "/trace", "/trace?last=60", "/queries"):
                code, body = _get(svc, path)
                assert code == 200, (path, body[:200])
            code, prom = _get(svc, "/metrics.prom")
            fams = _parse_prom(prom)
            assert "auron_engine_batches_total" in prom
            while api.next_batch(h) is not None:
                pass
            api.finalize_native(h)
        # after the trace closes: /queries serves its summary,
        # /trace?trace=<id> filters to it
        code, body = _get(svc, "/queries")
        assert code == 200
        qs = json.loads(body)
        assert any(q["trace_id"] == qt.trace.id for q in qs)
        code, body = _get(svc, f"/trace?trace={qt.trace.id}")
        ct = json.loads(body)
        xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
        assert xs and all(e["pid"] == qt.trace.id for e in xs)
        assert fams  # the live-query exposition had samples
    finally:
        api.remove_resource("http_live")


def test_prom_label_escaping_and_single_family():
    """Renderer-level exposition checks with hostile label values."""
    from auron_tpu.obs.export import render_prometheus

    body = render_prometheus(
        tasks={
            "1": {"stage": 0, "partition": 0,
                  "ops": {'We"ird\\Op\n': {"elapsed_compute": 5}}},
            "2": {"stage": 1, "partition": 1,
                  "ops": {'We"ird\\Op\n': {"elapsed_compute": 7}}},
        },
        counters={"compiles": 1, "host_syncs": 2},
        memory={"budget_bytes": 10, "num_spills": 0,
                "consumers": [{"name": "dup", "mem_used": 3},
                              {"name": "dup", "mem_used": 4}]},
        queries=0,
    )
    fams = _parse_prom(body)
    assert len(fams["auron_op_metric"]) == 2
    # duplicate consumer names collapse to one summed series
    assert fams["auron_memory_consumer_bytes"] == [
        'auron_memory_consumer_bytes{consumer="dup"} 7'
    ]
    assert '\\"' in body and "\\\\" in body and "\\n" in body


def test_handler_exception_500s_but_never_kills_service_or_task(
    svc, monkeypatch
):
    def boom() -> dict:
        raise RuntimeError("kaboom")

    monkeypatch.setattr(httpsvc, "_metrics_payload", boom)
    b = Batch.from_pydict({"v": list(range(100))},
                          schema=T.Schema.of(T.Field("v", T.INT64)))
    api.put_resource("http_boom", [[b]])
    try:
        plan = B.hash_agg(B.memory_scan(b.schema, "http_boom"), [],
                          [("sum", col(0), "s")], "partial")
        h = api.call_native(B.task(plan).SerializeToString())
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(svc, "/metrics")
        assert ei.value.code == 500
        # the service survives ...
        code, _ = _get(svc, "/healthz")
        assert code == 200
        # ... and so does the live task
        out = []
        while (rb := api.next_batch(h)) is not None:
            out.append(rb)
        assert sum(rb.column(0)[0].as_py() for rb in out) == sum(range(100))
        api.finalize_native(h)
    finally:
        api.remove_resource("http_boom")


def test_metrics_snapshot_hammer_under_mutation(svc):
    """Satellite: /metrics (and MetricNode.snapshot underneath) must
    tolerate operator threads mutating the tree mid-snapshot — the old
    dict() copy could raise 'dictionary changed size during iteration'
    and 500 the endpoint mid-query."""
    import threading

    from auron_tpu.exec.metrics import MetricNode

    node = MetricNode("root")
    stop = threading.Event()

    def mutate():
        i = 0
        while not stop.is_set():
            node.add(f"m{i % 997}", 1)
            node.child(i % 7).add("elapsed_compute", 1)
            i += 1

    t = threading.Thread(target=mutate, daemon=True)
    t.start()
    try:
        for _ in range(300):
            snap = node.snapshot()  # must never raise
            assert "values" in snap
    finally:
        stop.set()
        t.join()


def test_keepalive_connection_reused_across_requests(svc):
    """HTTP/1.1 persistence: many requests ride ONE socket (the serving
    clients' per-query connection-setup cost this removes)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", svc, timeout=10)
    try:
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 200 and not r.will_close
        r.read()
        sock = conn.sock
        assert sock is not None  # kept alive after the response
        for _ in range(3):
            conn.request("GET", "/conf")
            r = conn.getresponse()
            assert r.status == 200
            json.loads(r.read())
            assert conn.sock is sock  # same socket — no reconnect
    finally:
        conn.close()


def test_keepalive_post_sql_drains_body_on_early_return_paths(svc):
    """POST bodies must be consumed before ANY response (404 included):
    with keep-alive, unread bytes would be parsed as the next request."""
    import http.client

    class _Srv:
        def execute_json(self, body):
            return {"echo": body.get("sql")}

        def stats(self):
            return {}

    httpsvc.install_sql_server(_Srv())
    conn = http.client.HTTPConnection("127.0.0.1", svc, timeout=10)
    try:
        conn.request("POST", "/sql", body=json.dumps({"sql": "q1"}))
        r = conn.getresponse()
        assert r.status == 200 and json.loads(r.read())["echo"] == "q1"
        sock = conn.sock
        # bodied POST to an unknown path: the 404 must drain the body or
        # these 4096 bytes corrupt the kept-alive stream
        conn.request("POST", "/nope", body=b"x" * 4096)
        r = conn.getresponse()
        assert r.status == 404
        r.read()
        assert conn.sock is sock
        conn.request("POST", "/sql", body=json.dumps({"sql": "q2"}))
        r = conn.getresponse()
        assert r.status == 200 and json.loads(r.read())["echo"] == "q2"
        assert conn.sock is sock
    finally:
        conn.close()
        httpsvc.install_sql_server(None)


def test_keepalive_unacceptable_content_length_400s_and_closes(svc):
    """A Content-Length past _MAX_BODY is refused WITHOUT draining —
    the handler must advertise Connection: close, not pretend the
    stream is still framed."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", svc, timeout=10)
    try:
        conn.putrequest("POST", "/sql")
        conn.putheader("Content-Length", str(httpsvc._MAX_BODY + 1))
        conn.endheaders()
        r = conn.getresponse()
        assert r.status == 400
        assert r.will_close  # Connection: close advertised
        r.read()
    finally:
        conn.close()


def test_conf_gated_autostart():
    from auron_tpu.utils.config import Configuration

    assert httpsvc.maybe_start_from_conf(Configuration()) is None  # off by default
    port = httpsvc.maybe_start_from_conf(
        Configuration().set(httpsvc.HTTP_SERVICE_ENABLE, True)
    )
    try:
        assert port is not None
        code, _ = _get(port, "/healthz")
        assert code == 200
    finally:
        httpsvc.stop()

"""ICI all-to-all exchange tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from auron_tpu.parallel.exchange import sharded_agg_exchange_step
from auron_tpu.parallel.mesh import make_mesh, shard_rows


def test_sharded_agg_exchange_matches_pandas():
    mesh = make_mesh(8)
    P = 8
    cap = 256
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 40, (P, cap)).astype(np.int64)
    vals = rng.normal(size=(P, cap))
    sel = rng.random((P, cap)) < 0.9

    step = sharded_agg_exchange_step(mesh, slot_cap=cap)
    k = shard_rows(mesh, jnp.asarray(keys))
    v = shard_rows(mesh, jnp.asarray(vals))
    s = shard_rows(mesh, jnp.asarray(sel))
    fk, fs, fc, fv, overflow = jax.device_get(step(k, v, s))
    assert int(overflow) == 0

    got = {}
    for p in range(P):
        for key, sm, cnt, valid in zip(fk[p], fs[p], fc[p], fv[p]):
            if valid:
                assert key not in got, "group split across shards"
                got[int(key)] = (float(sm), int(cnt))

    df = pd.DataFrame({"k": keys.reshape(-1), "v": vals.reshape(-1),
                       "sel": sel.reshape(-1)})
    df = df[df.sel]
    want = df.groupby("k").agg(s=("v", "sum"), c=("v", "size"))
    assert set(got) == set(want.index.tolist())
    for key, (sm, cnt) in got.items():
        assert cnt == want.loc[key, "c"]
        assert sm == pytest.approx(want.loc[key, "s"], rel=1e-9)


def test_exchange_routing_is_spark_exact():
    """Group owner must equal pmod(murmur3(key), P) — same as file shuffle."""
    mesh = make_mesh(8)
    P = 8
    cap = 128
    keys = np.arange(P * cap, dtype=np.int64).reshape(P, cap) % 97
    vals = np.ones((P, cap))
    sel = np.ones((P, cap), bool)
    step = sharded_agg_exchange_step(mesh, slot_cap=cap)
    fk, fs, fc, fv, overflow = jax.device_get(
        step(*(shard_rows(mesh, jnp.asarray(a)) for a in (keys, vals, sel)))
    )
    assert int(overflow) == 0
    from auron_tpu.ops import hashing as H

    for p in range(P):
        live_keys = fk[p][fv[p]]
        if len(live_keys):
            expect = np.asarray(
                H.pmod(H.murmur3_i64(jnp.asarray(live_keys), jnp.uint32(42)).view(jnp.int32), P)
            )
            assert (expect == p).all()


def test_exchange_overflow_detection():
    """slot_cap smaller than rows per destination must raise the flag."""
    mesh = make_mesh(8)
    P = 8
    cap = 128
    # distinct keys -> no partial-agg collapse -> ~cap/P rows per destination
    # per shard, far above slot_cap=4
    keys = np.arange(P * cap, dtype=np.int64).reshape(P, cap)
    vals = np.ones((P, cap))
    sel = np.ones((P, cap), bool)
    step = sharded_agg_exchange_step(mesh, slot_cap=4)
    *_, overflow = jax.device_get(
        step(*(shard_rows(mesh, jnp.asarray(a)) for a in (keys, vals, sel)))
    )
    assert int(overflow) > 0


def test_generic_batch_exchange_mixed_dtypes():
    """Any column set rides the ICI exchange; co-location is murmur3-exact."""
    from auron_tpu.parallel.exchange import batch_exchange_step

    mesh = make_mesh(8)
    Pn, cap = 8, 128
    rng = np.random.default_rng(51)
    keys = rng.integers(0, 30, (Pn, cap)).astype(np.int64)
    vals_f = rng.normal(size=(Pn, cap))
    vals_i = rng.integers(0, 100, (Pn, cap)).astype(np.int32)
    valid = rng.random((Pn, cap)) < 0.8
    sel = np.ones((Pn, cap), bool)

    step = batch_exchange_step(mesh, slot_cap=cap)
    (rk,), payload, rsel, overflow = jax.device_get(
        step(
            (shard_rows(mesh, jnp.asarray(keys)),),
            {
                "f": shard_rows(mesh, jnp.asarray(vals_f)),
                "i": shard_rows(mesh, jnp.asarray(vals_i)),
                "m": shard_rows(mesh, jnp.asarray(valid)),
            },
            shard_rows(mesh, jnp.asarray(sel)),
        )
    )
    assert int(overflow) == 0
    # all rows arrive, and each key lands only on its murmur3 owner
    from auron_tpu.ops import hashing as H

    total = int(rsel.sum())
    assert total == Pn * cap
    for p in range(Pn):
        live = rsel[p].reshape(-1)
        ks = rk[p].reshape(-1)[live]
        if len(ks):
            owners = np.asarray(
                H.pmod(H.murmur3_i64(jnp.asarray(ks), jnp.uint32(42)).view(jnp.int32), Pn)
            )
            assert (owners == p).all()
    # payload integrity: global multiset of (key, i-value) preserved
    sent = sorted(zip(keys.reshape(-1).tolist(), vals_i.reshape(-1).tolist()))
    got = []
    for p in range(Pn):
        live = rsel[p].reshape(-1)
        got += list(zip(rk[p].reshape(-1)[live].tolist(),
                        payload["i"][p].reshape(-1)[live].tolist()))
    assert sorted(got) == sent

"""Parquet scan pruning: row-group statistics + late materialization +
coalesced remote reads (VERDICT r1 item 6; reference parquet_exec.rs:172-197,
scan/internal_file_reader.rs:47-52)."""

import io

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from auron_tpu import types as T
from auron_tpu.exec.base import ExecutionContext
from auron_tpu.exec.scan import CoalescedReadFile, ParquetScanExec
from auron_tpu.exprs.ir import BinaryOp, col, lit


@pytest.fixture(scope="module")
def pq_file(tmp_path_factory):
    """4 row groups with disjoint k ranges (sorted -> tight stats)."""
    path = str(tmp_path_factory.mktemp("scan") / "t.parquet")
    n = 4000
    df = pd.DataFrame(
        {
            "k": np.arange(n, dtype=np.int64),
            "v": (np.arange(n, dtype=np.int64) % 100) * 2,  # evens 0..198
            "s": [f"val_{i % 50}" for i in range(n)],
        }
    )
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), path,
                   row_group_size=1000)
    return path, df


def _scan(path, preds, **conf):
    schema = T.Schema.of(
        T.Field("k", T.INT64), T.Field("v", T.INT64), T.Field("s", T.STRING)
    )
    op = ParquetScanExec(schema, [path], preds)
    ctx = ExecutionContext()
    for k, v in conf.items():
        ctx.conf.set(k, v)
    batches = list(op.execute(0, ctx))
    rows = []
    for b in batches:
        rows.extend(b.to_arrow().to_pylist())
    return rows, ctx.metrics.snapshot()["values"]


def test_row_group_stats_pruning(pq_file):
    path, df = pq_file
    # k in [1200, 1800): only row group 1 (rows 1000-2000) can match
    preds = [BinaryOp("and", BinaryOp("gteq", col(0), lit(1200)),
                      BinaryOp("lt", col(0), lit(1800)))]
    rows, m = _scan(path, preds)
    want = df[(df.k >= 1200) & (df.k < 1800)]
    assert len(rows) == len(want)
    assert m["row_groups_total"] == 4
    assert m["row_groups_pruned"] == 3  # decoded only 1 of 4 groups
    # bytes_scanned drops vs an unpruned scan
    _, m_full = _scan(path, [])
    assert m["bytes_scanned"] < m_full["bytes_scanned"] / 2


def test_late_materialization_prunes_stat_blind_groups(pq_file):
    path, df = pq_file
    # v == 51 is inside every group's stats range [0, 198] but absent
    # (v is always even) -> stats can't prune; the pre-scan must
    preds = [BinaryOp("eq", col(1), lit(51))]
    rows, m = _scan(path, preds)
    assert rows == []
    assert m.get("row_groups_pruned", 0) == 0
    assert m["row_groups_pruned_late"] == 4
    # only the narrow predicate column was decoded
    _, m_full = _scan(path, [])
    assert m["bytes_scanned"] < m_full["bytes_scanned"] / 3

    # disabling the conf goes back to wide decode (still correct)
    rows2, m2 = _scan(path, preds, **{"parquet.late.materialization": False})
    assert rows2 == []
    assert m2.get("row_groups_pruned_late", 0) == 0


def test_pruned_scan_matches_exact_filter(pq_file):
    path, df = pq_file
    preds = [BinaryOp("and", BinaryOp("gt", col(0), lit(2500)),
                      BinaryOp("eq", col(1), lit(14)))]
    rows, m = _scan(path, preds)
    want = df[(df.k > 2500) & (df.v == 14)]
    assert [r["k"] for r in rows] == want.k.tolist()
    assert m["row_groups_pruned"] >= 2


def test_coalesced_reader_through_opener(pq_file):
    path, df = pq_file

    class CountingRaw(io.FileIO):
        reads = 0

        def read(self, n=-1):
            CountingRaw.reads += 1
            return super().read(n)

    schema = T.Schema.of(
        T.Field("k", T.INT64), T.Field("v", T.INT64), T.Field("s", T.STRING)
    )
    op = ParquetScanExec(
        schema, [path],
        [BinaryOp("lt", col(0), lit(500))],
        fs_resource_id="fs",
    )
    ctx = ExecutionContext(resources={"fs": lambda p: CountingRaw(p, "rb")})
    rows = []
    for b in op.execute(0, ctx):
        rows.extend(b.to_arrow().to_pylist())
    assert len(rows) == 500
    m = ctx.metrics.snapshot()["values"]
    # the whole file fits one over-read window: a handful of raw reads
    assert m["fs_raw_reads"] <= 4, m
    assert CountingRaw.reads <= 4
    assert m["row_groups_pruned"] == 3


def test_all_null_group_pruned_by_isnotnull(tmp_path):
    from auron_tpu.exprs.ir import IsNotNull

    path = str(tmp_path / "nulls.parquet")
    tbl = pa.table({"a": pa.array([None] * 100 + list(range(100)), pa.int64())})
    pq.write_table(tbl, path, row_group_size=100)
    schema = T.Schema.of(T.Field("a", T.INT64))
    rows, m = _scan_one(path, schema, [IsNotNull(col(0))])
    assert len(rows) == 100
    assert m["row_groups_pruned"] == 1


def _scan_one(path, schema, preds):
    op = ParquetScanExec(schema, [path], preds)
    ctx = ExecutionContext()
    rows = []
    for b in op.execute(0, ctx):
        rows.extend(b.to_arrow().to_pylist())
    return rows, ctx.metrics.snapshot()["values"]


def test_schema_adaption_missing_and_widened_columns(tmp_path):
    """Files written before a table gained a column (or with narrower
    physical types) read correctly: missing -> NULL, int32 -> int64
    (AuronSchemaAdapterFactory analog)."""
    old = str(tmp_path / "old.parquet")
    new = str(tmp_path / "new.parquet")
    pq.write_table(pa.table({"k": pa.array([1, 2], pa.int32())}), old)
    pq.write_table(
        pa.table({"k": pa.array([3, 4], pa.int32()),
                  "extra": pa.array(["x", "y"], pa.string())}),
        new,
    )
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("extra", T.STRING))
    op = ParquetScanExec(schema, [old, new])
    ctx = ExecutionContext()
    rows = []
    for b in op.execute(0, ctx):
        rows.extend(b.to_arrow().to_pylist())
    rows.sort(key=lambda r: r["k"])
    assert [r["k"] for r in rows] == [1, 2, 3, 4]
    assert [r["extra"] for r in rows] == [None, None, "x", "y"]


def test_schema_adaption_with_predicates(tmp_path):
    """late materialization stays correct when the predicate column is
    missing from a file (all-NULL -> pruned by IsNotNull-style filters)."""
    from auron_tpu.exprs.ir import BinaryOp

    a = str(tmp_path / "a.parquet")
    b = str(tmp_path / "b.parquet")
    pq.write_table(pa.table({"k": pa.array(range(10), pa.int64())}), a)
    pq.write_table(
        pa.table({"k": pa.array(range(10, 20), pa.int64()),
                  "v": pa.array(range(10), pa.int64())}),
        b,
    )
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64))
    op = ParquetScanExec(schema, [a, b], [BinaryOp("gteq", col(1), lit(5))])
    ctx = ExecutionContext()
    rows = []
    for bt in op.execute(0, ctx):
        rows.extend(bt.to_arrow().to_pylist())
    # file a has no v at all -> its rows all filtered; file b keeps v>=5
    assert sorted(r["k"] for r in rows) == list(range(15, 20))
    m = ctx.metrics.snapshot()["values"]
    assert m.get("row_groups_pruned_late", 0) >= 1  # file a probe: 0 matches


def test_orc_late_materialization_and_adaption(tmp_path):
    import pyarrow.orc as orc

    from auron_tpu.exec.scan import OrcScanExec
    from auron_tpu.exprs.ir import BinaryOp

    path = str(tmp_path / "t.orc")
    n = 3000
    tbl = pa.table({"k": pa.array(range(n), pa.int64()),
                    "v": pa.array([i % 50 for i in range(n)], pa.int64())})
    orc.write_table(tbl, path, stripe_size=8192)  # several stripes

    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64),
                         T.Field("missing", T.STRING))
    op = OrcScanExec(schema, [path], [BinaryOp("eq", col(1), lit(777))])
    ctx = ExecutionContext()
    rows = []
    for b in op.execute(0, ctx):
        rows.extend(b.to_arrow().to_pylist())
    assert rows == []  # v==777 never occurs
    m = ctx.metrics.snapshot()["values"]
    assert m.get("stripes_pruned_late", 0) >= 1  # probe skipped wide decodes

    op2 = OrcScanExec(schema, [path], [BinaryOp("lt", col(0), lit(3))])
    ctx2 = ExecutionContext()
    rows2 = []
    for b in op2.execute(0, ctx2):
        rows2.extend(b.to_arrow().to_pylist())
    assert [r["k"] for r in rows2] == [0, 1, 2]
    assert all(r["missing"] is None for r in rows2)  # schema adaption


# ---------------------------------------------------------------------------
# late materialization decodes predicate columns ONCE (ISSUE 12 satellite):
# a surviving row group/stripe reuses the probe's decoded plane for the
# emitted batch instead of re-reading the predicate columns in the wide
# decode — pinned by spying on the reader's per-call column lists.
# ---------------------------------------------------------------------------


def _no_column_read_twice(calls):
    """calls: [(group/stripe, columns)] — no column may be requested twice
    for the same group."""
    seen: dict = {}
    for g, cols_req in calls:
        for c in cols_req:
            assert c not in seen.setdefault(g, set()), (
                f"column {c!r} decoded twice for group {g}")
            seen[g].add(c)


def test_parquet_probe_plane_reused_not_double_decoded(
    tmp_path, monkeypatch
):
    path = str(tmp_path / "t.parquet")
    n = 4000
    tbl = pa.table({"k": pa.array(range(n), pa.int64()),
                    "v": pa.array([i % 7 for i in range(n)], pa.int64()),
                    "w": pa.array([float(i) for i in range(n)])})
    pq.write_table(tbl, path, row_group_size=1000)

    calls = []
    orig = pq.ParquetFile.read_row_group

    def spy(self, rg, columns=None, **kw):
        calls.append((rg, tuple(columns or ())))
        return orig(self, rg, columns=columns, **kw)

    monkeypatch.setattr(pq.ParquetFile, "read_row_group", spy)
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64),
                         T.Field("w", T.FLOAT64))
    # v == 3 survives in every group -> every group probes AND emits
    op = ParquetScanExec(schema, [path], [BinaryOp("eq", col(1), lit(3))])
    ctx = ExecutionContext()
    rows = [r for b in op.execute(0, ctx)
            for r in b.to_arrow().to_pylist()]
    assert len(rows) == sum(1 for i in range(n) if i % 7 == 3)
    _no_column_read_twice(calls)
    # the surviving groups requested v exactly once (the probe), and the
    # wide read asked only for the REST of the schema
    wide = [cols_req for _, cols_req in calls if "v" not in cols_req]
    assert wide and all(set(c) == {"k", "w"} for c in wide)

    # bit-identity vs the late-materialization-off decode
    from auron_tpu.utils.config import PARQUET_LATE_MATERIALIZATION, Configuration

    op2 = ParquetScanExec(schema, [path], [BinaryOp("eq", col(1), lit(3))])
    ctx2 = ExecutionContext(
        conf=Configuration().set(PARQUET_LATE_MATERIALIZATION, False))
    rows2 = [r for b in op2.execute(0, ctx2)
             for r in b.to_arrow().to_pylist()]
    assert rows == rows2


def test_orc_probe_plane_reused_not_double_decoded(tmp_path, monkeypatch):
    orc = pytest.importorskip("pyarrow.orc")

    from auron_tpu.exec.scan import OrcScanExec

    path = str(tmp_path / "probe.orc")
    n = 3000
    tbl = pa.table({"k": pa.array(range(n), pa.int64()),
                    "v": pa.array([i % 5 for i in range(n)], pa.int64())})
    orc.write_table(tbl, path, stripe_size=8192)

    calls = []
    orig = orc.ORCFile.read_stripe

    def spy(self, i, columns=None, **kw):
        calls.append((i, tuple(columns or ())))
        return orig(self, i, columns=columns, **kw)

    monkeypatch.setattr(orc.ORCFile, "read_stripe", spy)
    schema = T.Schema.of(T.Field("k", T.INT64), T.Field("v", T.INT64),
                         T.Field("missing", T.STRING))
    op = OrcScanExec(schema, [path], [BinaryOp("eq", col(1), lit(2))])
    ctx = ExecutionContext()
    rows = [r for b in op.execute(0, ctx)
            for r in b.to_arrow().to_pylist()]
    assert len(rows) == sum(1 for i in range(n) if i % 5 == 2)
    assert all(r["missing"] is None for r in rows)
    _no_column_read_twice(calls)
    wide = [cols_req for _, cols_req in calls if "v" not in cols_req]
    assert wide and all(set(c) == {"k"} for c in wide)

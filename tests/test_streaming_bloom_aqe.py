"""Streaming Calc, bloom filter, broadcast, AQE statistics tests."""

import json

import numpy as np
import pytest

from auron_tpu import types as T
from auron_tpu.exec.streaming import (
    EARLIEST, LATEST, JsonRowDeserializer, MockKafkaSource, StreamingCalcExec,
)
from auron_tpu.exprs.ir import BinaryOp, ScalarFunc, col, lit


def _records(n, start=0):
    return [json.dumps({"id": i, "v": i * 1.5, "s": f"u{i % 3}"}).encode()
            for i in range(start, start + n)]


def _calc(source, schema):
    return StreamingCalcExec(
        source=source,
        deserializer=JsonRowDeserializer(schema),
        in_schema=schema,
        predicates=[BinaryOp("gteq", col(0), lit(3))],
        projections=[(col(0), "id"), (BinaryOp("mul", col(1), lit(2.0)), "v2")],
        max_batch_records=4,
    )


def test_streaming_calc_earliest():
    schema = T.Schema.of(T.Field("id", T.INT64), T.Field("v", T.FLOAT64),
                         T.Field("s", T.STRING))
    src = MockKafkaSource([_records(5), _records(5, start=5)])
    out = []
    for b in _calc(src, schema).run():
        out += b.to_pydict()["id"]
    assert sorted(out) == list(range(3, 10))
    assert src.offsets() == {0: 5, 1: 5}


def test_streaming_startup_modes():
    schema = T.Schema.of(T.Field("id", T.INT64), T.Field("v", T.FLOAT64),
                         T.Field("s", T.STRING))
    src = MockKafkaSource([_records(5)], startup_mode=LATEST)
    assert list(_calc(src, schema).run()) == []
    src2 = MockKafkaSource([_records(5)], startup_mode="offsets", start_offsets={0: 4})
    out = []
    for b in _calc(src2, schema).run():
        out += b.to_pydict()["id"]
    assert out == [4]


def test_streaming_bad_records_become_nulls():
    schema = T.Schema.of(T.Field("id", T.INT64), T.Field("v", T.FLOAT64),
                         T.Field("s", T.STRING))
    src = MockKafkaSource([[b"not json", json.dumps({"id": 7, "v": 1.0, "s": "x"}).encode()]])
    out = []
    for b in _calc(src, schema).run():
        out += b.to_pydict()["id"]
    assert out == [7]  # bad record -> null id -> filtered by predicate


def test_bloom_filter_no_false_negatives():
    import jax.numpy as jnp

    from auron_tpu.ops.bloom import SparkBloomFilter

    rng = np.random.default_rng(23)
    items = jnp.asarray(rng.integers(-(2**62), 2**62, 5000))
    bf = SparkBloomFilter.create(5000, fpp=0.03)
    bf.put_long(items)
    assert bool(bf.might_contain_long(items).all())
    others = jnp.asarray(rng.integers(-(2**62), 2**62, 5000))
    fp = float(bf.might_contain_long(others).mean())
    assert fp < 0.1
    # serde roundtrip
    bf2 = SparkBloomFilter.deserialize(bf.serialize())
    assert bool(bf2.might_contain_long(items).all())


def test_bloom_might_contain_expr():
    import jax.numpy as jnp

    from auron_tpu.columnar import Batch
    from auron_tpu.exec.basic import MemoryScanExec, ProjectExec
    from auron_tpu.ops.bloom import SparkBloomFilter

    bf = SparkBloomFilter.create(10)
    bf.put_long(jnp.asarray([5, 7, 9], dtype=jnp.int64))
    payload = bf.serialize()
    b = Batch.from_pydict({"x": [5, 6, 7, 8]},
                          schema=T.Schema.of(T.Field("x", T.INT64)))
    p = ProjectExec(
        MemoryScanExec.single([b]),
        [ScalarFunc("bloom_filter_might_contain", (lit(payload, T.BINARY), col(0)))],
        ["hit"],
    )
    out = p.collect_pydict()["hit"]
    assert out[0] is True and out[2] is True  # no false negatives


def test_broadcast_and_aqe(tmp_path):
    from auron_tpu.columnar import Batch
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.parallel.broadcast import (
        batches_from_ipc, collect_ipc, map_output_stats, plan_coalesced_partitions,
    )

    b = Batch.from_pydict({"x": [1, 2, 3]})
    blocks = collect_ipc(MemoryScanExec.single([b]))
    back = batches_from_ipc(blocks)
    assert back[0].to_pydict() == {"x": [1, 2, 3]}

    # AQE stats over real shuffle indexes
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.shuffle import HashPartitioning, ShuffleWriterExec
    from auron_tpu.exprs.ir import col as c

    idxs = []
    for m in range(2):
        scan = MemoryScanExec.single([Batch.from_pydict({"k": list(range(100))})])
        d, i = str(tmp_path / f"m{m}.data"), str(tmp_path / f"m{m}.index")
        list(ShuffleWriterExec(scan, HashPartitioning([c(0)], 8), d, i).execute(0, ExecutionContext()))
        idxs.append(i)
    stats = map_output_stats(idxs)
    assert len(stats) == 8 and stats.sum() > 0
    groups = plan_coalesced_partitions(stats, target_bytes=int(stats.sum() // 3))
    assert sum(len(g) for g in groups) == 8
    assert len(groups) <= 4

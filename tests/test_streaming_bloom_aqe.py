"""Streaming Calc, bloom filter, broadcast, AQE statistics tests."""

import json

import numpy as np
import pytest

from auron_tpu import types as T
from auron_tpu.exec.streaming import (
    EARLIEST, LATEST, JsonRowDeserializer, MockKafkaSource, StreamingCalcExec,
)
from auron_tpu.exprs.ir import BinaryOp, ScalarFunc, col, lit


def _records(n, start=0):
    return [json.dumps({"id": i, "v": i * 1.5, "s": f"u{i % 3}"}).encode()
            for i in range(start, start + n)]


def _calc(source, schema):
    return StreamingCalcExec(
        source=source,
        deserializer=JsonRowDeserializer(schema),
        in_schema=schema,
        predicates=[BinaryOp("gteq", col(0), lit(3))],
        projections=[(col(0), "id"), (BinaryOp("mul", col(1), lit(2.0)), "v2")],
        max_batch_records=4,
    )


def test_streaming_calc_earliest():
    schema = T.Schema.of(T.Field("id", T.INT64), T.Field("v", T.FLOAT64),
                         T.Field("s", T.STRING))
    src = MockKafkaSource([_records(5), _records(5, start=5)])
    out = []
    for b in _calc(src, schema).run():
        out += b.to_pydict()["id"]
    assert sorted(out) == list(range(3, 10))
    assert src.offsets() == {0: 5, 1: 5}


def test_streaming_startup_modes():
    schema = T.Schema.of(T.Field("id", T.INT64), T.Field("v", T.FLOAT64),
                         T.Field("s", T.STRING))
    src = MockKafkaSource([_records(5)], startup_mode=LATEST)
    assert list(_calc(src, schema).run()) == []
    src2 = MockKafkaSource([_records(5)], startup_mode="offsets", start_offsets={0: 4})
    out = []
    for b in _calc(src2, schema).run():
        out += b.to_pydict()["id"]
    assert out == [4]


def test_streaming_bad_records_become_nulls():
    schema = T.Schema.of(T.Field("id", T.INT64), T.Field("v", T.FLOAT64),
                         T.Field("s", T.STRING))
    src = MockKafkaSource([[b"not json", json.dumps({"id": 7, "v": 1.0, "s": "x"}).encode()]])
    out = []
    for b in _calc(src, schema).run():
        out += b.to_pydict()["id"]
    assert out == [7]  # bad record -> null id -> filtered by predicate


def test_bloom_filter_no_false_negatives():
    import jax.numpy as jnp

    from auron_tpu.ops.bloom import SparkBloomFilter

    rng = np.random.default_rng(23)
    items = jnp.asarray(rng.integers(-(2**62), 2**62, 5000))
    bf = SparkBloomFilter.create(5000, fpp=0.03)
    bf.put_long(items)
    assert bool(bf.might_contain_long(items).all())
    others = jnp.asarray(rng.integers(-(2**62), 2**62, 5000))
    fp = float(bf.might_contain_long(others).mean())
    assert fp < 0.1
    # serde roundtrip
    bf2 = SparkBloomFilter.deserialize(bf.serialize())
    assert bool(bf2.might_contain_long(items).all())


def test_bloom_might_contain_expr():
    import jax.numpy as jnp

    from auron_tpu.columnar import Batch
    from auron_tpu.exec.basic import MemoryScanExec, ProjectExec
    from auron_tpu.ops.bloom import SparkBloomFilter

    bf = SparkBloomFilter.create(10)
    bf.put_long(jnp.asarray([5, 7, 9], dtype=jnp.int64))
    payload = bf.serialize()
    b = Batch.from_pydict({"x": [5, 6, 7, 8]},
                          schema=T.Schema.of(T.Field("x", T.INT64)))
    p = ProjectExec(
        MemoryScanExec.single([b]),
        [ScalarFunc("bloom_filter_might_contain", (lit(payload, T.BINARY), col(0)))],
        ["hit"],
    )
    out = p.collect_pydict()["hit"]
    assert out[0] is True and out[2] is True  # no false negatives


def test_broadcast_and_aqe(tmp_path):
    from auron_tpu.columnar import Batch
    from auron_tpu.exec.basic import MemoryScanExec
    from auron_tpu.parallel.broadcast import (
        batches_from_ipc, collect_ipc, map_output_stats, plan_coalesced_partitions,
    )

    b = Batch.from_pydict({"x": [1, 2, 3]})
    blocks = collect_ipc(MemoryScanExec.single([b]))
    back = batches_from_ipc(blocks)
    assert back[0].to_pydict() == {"x": [1, 2, 3]}

    # AQE stats over real shuffle indexes
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.shuffle import HashPartitioning, ShuffleWriterExec
    from auron_tpu.exprs.ir import col as c

    idxs = []
    for m in range(2):
        scan = MemoryScanExec.single([Batch.from_pydict({"k": list(range(100))})])
        d, i = str(tmp_path / f"m{m}.data"), str(tmp_path / f"m{m}.index")
        list(ShuffleWriterExec(scan, HashPartitioning([c(0)], 8), d, i).execute(0, ExecutionContext()))
        idxs.append(i)
    stats = map_output_stats(idxs)
    assert len(stats) == 8 and stats.sum() > 0
    groups = plan_coalesced_partitions(stats, target_bytes=int(stats.sum() // 3))
    assert sum(len(g) for g in groups) == 8
    assert len(groups) <= 4


# ---------------------------------------------------------------------------
# round 2: protobuf serde, error policies, kafka_scan in the plan IR
# ---------------------------------------------------------------------------


def _pb_varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_record(id_v=None, price=None, name=None) -> bytes:
    """Encode {1: int64 id, 2: double price, 3: string name}."""
    import struct

    out = bytearray()
    if id_v is not None:
        out += _pb_varint((1 << 3) | 0) + _pb_varint(id_v)
    if price is not None:
        out += _pb_varint((2 << 3) | 1) + struct.pack("<d", price)
    if name is not None:
        nb = name.encode()
        out += _pb_varint((3 << 3) | 2) + _pb_varint(len(nb)) + nb
    return bytes(out)


PB_SCHEMA = T.Schema.of(T.Field("id", T.INT64), T.Field("price", T.FLOAT64),
                        T.Field("name", T.STRING))


def test_protobuf_row_deserializer():
    from auron_tpu.exec.streaming import ProtobufRowDeserializer

    de = ProtobufRowDeserializer(PB_SCHEMA)
    rb = de.deserialize([
        _pb_record(1, 9.5, "a"),
        _pb_record(-2, None, "b"),   # missing field -> NULL
        _pb_record(3, 0.25, None),
    ])
    got = rb.to_pydict()
    assert got["id"] == [1, -2, 3]
    assert got["price"] == [9.5, None, 0.25]
    assert got["name"] == ["a", "b", None]
    assert de.errors == 0


def test_deserializer_error_policies():
    from auron_tpu.exec.streaming import (
        DeserializeError, ProtobufRowDeserializer,
    )

    bad = b"\xff\xff\xff"  # truncated varint
    rows = [_pb_record(1, 1.0, "x"), bad, _pb_record(2, 2.0, "y")]

    de = ProtobufRowDeserializer(PB_SCHEMA, on_error="skip")
    rb = de.deserialize(rows)
    assert rb.to_pydict()["id"] == [1, 2] and de.errors == 1

    de2 = ProtobufRowDeserializer(PB_SCHEMA, on_error="null")
    rb2 = de2.deserialize(rows)
    assert rb2.to_pydict()["id"] == [1, None, 2] and de2.errors == 1

    de3 = ProtobufRowDeserializer(PB_SCHEMA, on_error="fail")
    with pytest.raises(DeserializeError):
        de3.deserialize(rows)


def test_planned_kafka_scan_calc_query():
    """kafka_scan is a first-class plan node: a streaming Calc query built
    from proto bytes runs through the normal task runtime."""
    from auron_tpu.bridge import api
    from auron_tpu.exec.streaming import MockKafkaSource
    from auron_tpu.plan import builders as B

    records = [_pb_record(i, i * 2.0, f"n{i}") for i in range(10)]
    api.put_resource(
        "kafka_src",
        lambda topic, mode, offsets: MockKafkaSource(
            [records], startup_mode=mode, start_offsets=offsets
        ),
    )
    try:
        scan = B.kafka_scan(PB_SCHEMA, "orders", "kafka_src",
                            data_format="protobuf", on_error="skip")
        calc = B.project(
            B.filter_(scan, [BinaryOp("gteq", col(0), lit(5))]),
            [(col(0), "id"), (BinaryOp("mul", col(1), lit(10.0)), "p10")],
        )
        h = api.call_native(B.task(calc).SerializeToString())
        ids, p10 = [], []
        while (rb := api.next_batch(h)) is not None:
            d = rb.to_pydict()
            ids += d["id"]
            p10 += d["p10"]
        metrics = api.finalize_native(h)
        assert ids == list(range(5, 10))
        assert p10 == [i * 20.0 for i in range(5, 10)]
        # checkpoint offsets surfaced for resume
        assert api.get_resource("kafka_src.offsets") is None  # task-scoped
    finally:
        api.remove_resource("kafka_src")


def test_planned_kafka_scan_offset_resume_and_error_metric():
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.streaming import KafkaScanExec, MockKafkaSource

    records = [_pb_record(i, float(i), "x") for i in range(6)] + [b"\xff"]
    src = MockKafkaSource([records], startup_mode="offsets", start_offsets={0: 4})
    op = KafkaScanExec(PB_SCHEMA, "t", "src", startup_mode="offsets",
                       start_offsets={0: 4}, data_format="protobuf",
                       on_error="skip")
    ctx = ExecutionContext(resources={"src": src})
    got = []
    for b in op.execute(0, ctx):
        got += b.to_pydict()["id"]
    assert got == [4, 5]  # resumed from offset 4; bad record skipped
    m = ctx.metrics.snapshot()["values"]
    assert m["deserialize_errors"] == 1
    assert ctx.resources["src.offsets"] == {0: 7}


def test_zigzag_sint_columns_via_plan():
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.streaming import MockKafkaSource
    from auron_tpu.plan import builders as B
    from auron_tpu.plan.planner import plan_from_proto

    def zz(v):  # zigzag encode
        return (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1

    recs = [_pb_varint((1 << 3) | 0) + _pb_varint(zz(v)) for v in (-1, -2, 3)]
    schema = T.Schema.of(T.Field("d", T.INT64))
    plan = B.kafka_scan(schema, "t", "zz_src", data_format="protobuf",
                        zigzag_cols=[0])
    op = plan_from_proto(plan)
    ctx = ExecutionContext(resources={"zz_src": MockKafkaSource([recs])})
    got = []
    for b in op.execute(0, ctx):
        got += b.to_pydict()["d"]
    assert got == [-1, -2, 3]


def test_offsets_surfaced_on_fail_abort():
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.streaming import DeserializeError, KafkaScanExec, MockKafkaSource

    src = MockKafkaSource([[_pb_record(1, 1.0, "a"), b"\xff", _pb_record(2, 2.0, "b")]])
    op = KafkaScanExec(PB_SCHEMA, "t", "src", data_format="protobuf",
                       on_error="fail")
    ctx = ExecutionContext(resources={"src": src})
    with pytest.raises(RuntimeError):  # wrapped by execute/pump? direct: DeserializeError
        try:
            list(op.execute(0, ctx))
        except DeserializeError as e:
            raise RuntimeError(str(e)) from e
    # abort path still surfaces checkpoint offsets + error count
    assert "src.offsets" in ctx.resources
    m = ctx.metrics.snapshot()["values"]
    assert m["deserialize_errors"] == 1


def test_unknown_format_fails_fast():
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.streaming import KafkaScanExec, MockKafkaSource

    op = KafkaScanExec(PB_SCHEMA, "t", "src", data_format="avro")
    ctx = ExecutionContext(resources={"src": MockKafkaSource([[b"{}"]])})
    with pytest.raises(ValueError, match="unsupported streaming format"):
        list(op.execute(0, ctx))

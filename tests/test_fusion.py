"""Whole-stage fusion gate (plan/fusion.py; docs/fusion.md).

Bit-identity is the contract: every pipeline the pass rewrites must
produce byte-for-byte the batches the eager operators produce, across
schemas, NULL patterns, capacity buckets, dictionary passthrough, the
partial-agg input rewrite, the dense-prep hand-off (including forced
re-anchors and a forced compaction-bucket mispredict downstream), and
the blocking-boundary rules. The retrace guard's accounting
(fusion_stats) is pinned here too: replaying a stream must not add
compiles, and compile count is bounded by programs x capacity buckets.
"""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from auron_tpu import types as T
from auron_tpu.columnar.batch import Batch
from auron_tpu.exec.agg_exec import AggExpr, HashAggExec
from auron_tpu.exec.basic import (
    FilterExec,
    LimitExec,
    MemoryScanExec,
    ProjectExec,
    RenameColumnsExec,
)
from auron_tpu.exec.joins import BroadcastHashJoinExec
from auron_tpu.exec.sort_exec import SortExec
from auron_tpu.exprs import ir
from auron_tpu.exprs.ir import BinaryOp, Case, Column, If, In, IsNull, Literal, Not
from auron_tpu.ops.sortkeys import SortSpec
from auron_tpu.plan import fusion
from auron_tpu.plan.fusion import (
    FusedStageExec,
    expr_trace_safe,
    fuse_exec_tree,
    fusion_stats,
    reset_fusion_stats,
)
from auron_tpu.utils.config import Configuration

ON = Configuration({"exec.fuse.enable": "on"})


def _walk(op):
    yield op
    for c in op.children:
        yield from _walk(c)


def _types(op):
    return [type(o).__name__ for o in _walk(op)]


def _frame(n, seed, nulls=False):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 50, n).astype(np.int64)
    v = rng.normal(size=n)
    q = rng.integers(0, 100, n).astype(np.int32)
    s = [f"s{int(x) % 9}" for x in rng.integers(0, 40, n)]
    d = {
        "k": k.tolist(), "v": v.tolist(), "q": q.tolist(), "s": s,
    }
    if nulls:
        d["k"] = [None if i % 7 == 0 else x for i, x in enumerate(d["k"])]
        d["v"] = [None if i % 5 == 0 else x for i, x in enumerate(d["v"])]
        d["s"] = [None if i % 11 == 0 else x for i, x in enumerate(d["s"])]
    schema = T.Schema((
        T.Field("k", T.INT64, True), T.Field("v", T.FLOAT64, True),
        T.Field("q", T.INT32, True), T.Field("s", T.STRING, True),
    ))
    return Batch.from_pydict(d, schema)


def _ab(build, sort_cols=None):
    """Collect the tree eager vs fused; assert identical; return fused."""
    plain = build().collect().to_pandas()
    fused_tree = fuse_exec_tree(build(), ON)
    fused = fused_tree.collect().to_pandas()
    if sort_cols:
        plain = plain.sort_values(sort_cols).reset_index(drop=True)
        fused = fused.sort_values(sort_cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(plain, fused)
    return fused_tree


# ---------------------------------------------------------------------------
# bit-identity fuzz
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("nulls", [False, True])
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_chain_bit_identity_fuzz(seed, nulls):
    """filter->project->filter->rename chains over varying capacity
    buckets and NULL patterns: fused output is bit-identical, including a
    dictionary-encoded passthrough column riding through the segment."""
    rng = np.random.default_rng(seed * 101)
    batches = [
        _frame(int(rng.integers(100, 3000)), seed * 10 + i, nulls)
        for i in range(4)
    ]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        f1 = FilterExec(scan, [
            BinaryOp("gt", Column(1, "v"), Literal(-0.5, T.FLOAT64)),
            In(Column(2, "q"), tuple(range(0, 90)), False),
        ])
        p = ProjectExec(f1, [
            BinaryOp("add", Column(0, "k"), Literal(1, T.INT64)),
            Case(((BinaryOp("lt", Column(2, "q"), Literal(10, T.INT32)),
                   Literal(0.0, T.FLOAT64)),), Column(1, "v")),
            Column(3, "s"),          # dict passthrough
            Not(IsNull(Column(0, "k"))),
        ], ["k1", "vc", "s", "kn"])
        f2 = FilterExec(p, [Column(3, "kn")])
        return RenameColumnsExec(f2, ["K", "V", "S", "KN"])

    tree = _ab(build)
    assert isinstance(tree, FusedStageExec), _types(tree)
    assert tree.fused_op_names() == ["FilterExec", "ProjectExec", "FilterExec"]


@pytest.mark.parametrize("seed", [0, 1])
def test_agg_prefusion_bit_identity(seed):
    """scan->filter->partial agg->final agg with the grouping/argument
    expressions compiled into the stage (incl. dense prep on the CPU
    host-scatter substrate): identical to the eager pipeline."""
    batches = [_frame(1500, seed * 7 + i, nulls=True) for i in range(5)]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        f = FilterExec(scan, [BinaryOp("gt", Column(2, "q"), Literal(20, T.INT32))])
        key = If(BinaryOp("lt", Column(2, "q"), Literal(60, T.INT32)),
                 Literal(None, T.INT64), Column(0, "k"))
        p = HashAggExec(f, [(key, "g")], [
            (AggExpr("sum", Column(1, "v")), "s"),
            (AggExpr("count_star", None), "c"),
            (AggExpr("min", Column(2, "q")), "lo"),
            (AggExpr("max", Column(1, "v")), "hi"),
            (AggExpr("avg", Column(1, "v")), "a"),
            (AggExpr("count", Column(1, "v")), "cv"),
        ], "partial")
        return HashAggExec(p, [(Column(0, "g"), "g")], [
            (AggExpr("sum", Column(1, "s")), "s"),
            (AggExpr("count_star", None), "c"),
            (AggExpr("min", Column(2, "lo")), "lo"),
            (AggExpr("max", Column(3, "hi")), "hi"),
            (AggExpr("avg", Column(4, "a")), "a"),
            (AggExpr("count", Column(6, "cv")), "cv"),
        ], "final")

    tree = _ab(build, sort_cols=["g"])
    partial = tree.children[0]
    assert isinstance(partial, HashAggExec)
    assert isinstance(partial.children[0], FusedStageExec)
    # the rewritten aggregate consumes bare column refs
    assert all(isinstance(g, Column) for g, _ in partial.groupings)
    assert partial.children[0].dense_link is not None


def test_dense_reanchor_under_prefusion():
    """Key range explodes mid-stream: the dense table drains, re-anchors
    and re-publishes; stale-epoch prepped batches refold via the raw path.
    Results stay identical to the eager pipeline."""
    frames = []
    for i in range(6):
        lo = 0 if i < 2 else 10_000_000 * i  # range jumps force restarts
        k = (np.arange(800) % 37 + lo).astype(np.int64)
        frames.append(Batch.from_pydict({
            "k": k.tolist(),
            "v": np.linspace(0, 1, 800).tolist(),
        }))

    def build():
        scan = MemoryScanExec([list(frames)], frames[0].schema)
        p = HashAggExec(scan, [(Column(0, "k"), "k")], [
            (AggExpr("sum", Column(1, "v")), "s"),
            (AggExpr("count_star", None), "c"),
        ], "partial")
        return HashAggExec(p, [(Column(0, "k"), "k")], [
            (AggExpr("sum", Column(1, "s")), "s"),
            (AggExpr("count_star", None), "c"),
        ], "final")

    _ab(build, sort_cols=["k"])


def test_fused_stage_feeding_join_chain_mispredict(monkeypatch):
    """A fused filter below a BHJ whose selectivity jumps ~0 -> ~100%
    mid-stream: the downstream compaction-bucket mispredict repair sees
    exactly the batches the eager filter would emit (bit-identical end
    result) — fusion must not disturb the predictor protocol."""
    n = 6000
    k0 = np.where(np.arange(n) < 1000, 999, np.arange(n) % 8).astype(np.int64)
    fact = pd.DataFrame({"k0": k0, "amt": np.arange(n, dtype=np.int64)})
    dim = pd.DataFrame({"id": np.arange(8, dtype=np.int64),
                        "dv": np.arange(8, dtype=np.int64) * 10})
    fact_b = [Batch.from_pandas(fact.iloc[i:i + 1000])
              for i in range(0, n, 1000)]
    dim_b = [Batch.from_pandas(dim)]

    def build():
        scan = MemoryScanExec([list(fact_b)], fact_b[0].schema)
        flt = FilterExec(scan, [BinaryOp(
            "gteq", Column(1, "amt"), Literal(0, T.INT64))])
        return BroadcastHashJoinExec(
            flt, MemoryScanExec([list(dim_b)], dim_b[0].schema),
            [Column(0, "k0")], [Column(0, "id")], "inner",
            build_side="right",
        )

    from auron_tpu.utils.config import JOIN_COMPACT_OUTPUT, active_conf
    conf = active_conf()
    saved = conf.get(JOIN_COMPACT_OUTPUT)
    conf.set(JOIN_COMPACT_OUTPUT, "on")
    try:
        tree = _ab(build, sort_cols=None)
    finally:
        conf.set(JOIN_COMPACT_OUTPUT, saved)
    assert "FusedStageExec" in _types(tree)


# ---------------------------------------------------------------------------
# blocking boundaries & trace safety
# ---------------------------------------------------------------------------


def test_segments_never_cross_blocking_boundaries():
    """Sort, join build and limit are boundaries: chains above and below
    fuse separately, never THROUGH the boundary operator."""
    batches = [_frame(500, 3)]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        f1 = FilterExec(scan, [BinaryOp("gt", Column(1, "v"), Literal(0.0, T.FLOAT64))])
        srt = SortExec(f1, [Column(0, "k")], [SortSpec(True, True)])
        f2 = FilterExec(srt, [BinaryOp("lt", Column(2, "q"), Literal(90, T.INT32))])
        lim = LimitExec(f2, 100)
        p = ProjectExec(lim, [Column(0, "k"), Column(1, "v")], ["k", "v"])
        return p

    tree = fuse_exec_tree(build(), ON)
    names = _types(tree)
    # project above limit fused alone; filter between sort and limit fused
    # alone; filter below sort fused alone — boundaries intact in between
    assert names.count("FusedStageExec") == 3
    i_sort = names.index("SortExec")
    i_lim = names.index("LimitExec")
    assert i_lim < i_sort  # limit sits above sort in this walk order
    for seg in (s for s in _walk(tree) if isinstance(s, FusedStageExec)):
        assert len(seg.fused_op_names()) == 1  # nothing fused ACROSS


def test_unsafe_exprs_split_segments():
    """A host-evaluated expression (LIKE over a dict column) splits the
    chain: safe runs around it fuse, the unsafe operator stays eager."""
    batches = [_frame(400, 4)]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        f1 = FilterExec(scan, [BinaryOp("gt", Column(1, "v"), Literal(-9.0, T.FLOAT64))])
        f2 = FilterExec(f1, [ir.Like(Column(3, "s"), "s1%", False, "\\")])
        f3 = FilterExec(f2, [BinaryOp("lt", Column(2, "q"), Literal(95, T.INT32))])
        return f3

    tree = _ab(build)
    names = _types(tree)
    assert names[:4] == ["FusedStageExec", "FilterExec", "FusedStageExec",
                         "MemoryScanExec"]


def test_trace_safety_rules():
    schema = _frame(10, 0).schema
    assert expr_trace_safe(BinaryOp("gt", Column(1, "v"), Literal(0.0, T.FLOAT64)), schema)
    assert expr_trace_safe(In(Column(2, "q"), (1, 2, 3), True), schema)
    # dict-encoded column: bare ref only with allow_dict_out
    assert not expr_trace_safe(Column(3, "s"), schema)
    assert expr_trace_safe(Column(3, "s"), schema, allow_dict_out=True)
    # IsNull over a dict column reads only validity — safe
    assert expr_trace_safe(IsNull(Column(3, "s")), schema)
    # string compare transforms dictionaries — not fusable
    assert not expr_trace_safe(
        BinaryOp("eq", Column(3, "s"), Literal("s1", T.STRING)), schema)
    # host UDFs never fuse
    assert not expr_trace_safe(
        ir.HostUDF("f", (Column(0, "k"),), T.INT64), schema)
    # row-offset context never fuses
    assert not expr_trace_safe(ir.RowNum(), schema)


def test_cost_model_substrate_selection():
    """auto on XLA:CPU fuses only segments whose eager dispatch estimate
    reaches exec.fuse.min.ops; on/off override unconditionally."""
    batches = [_frame(200, 5)]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        return ProjectExec(scan, [Column(0, "k")], ["k"])

    # 1 op + 1 expr node = cost 2; min.ops 50 rejects, 1 accepts (CPU auto)
    t1 = fuse_exec_tree(build(), Configuration(
        {"exec.fuse.enable": "auto", "exec.fuse.min.ops": 50}))
    assert not isinstance(t1, FusedStageExec)
    t2 = fuse_exec_tree(build(), Configuration(
        {"exec.fuse.enable": "auto", "exec.fuse.min.ops": 1}))
    assert isinstance(t2, FusedStageExec)
    t3 = fuse_exec_tree(build(), Configuration({"exec.fuse.enable": "off"}))
    assert not isinstance(t3, FusedStageExec)


# ---------------------------------------------------------------------------
# retrace discipline & metric attribution
# ---------------------------------------------------------------------------


def test_replay_adds_no_compiles():
    """The (schema, segment signature, capacity bucket) cache key is
    stable: replaying the same stream adds ZERO fused-segment compiles,
    and compile count stays bounded by programs x distinct buckets —
    the tools/perfcheck.py retrace guard's invariant."""
    batches = [_frame(100, 6), _frame(1000, 7), _frame(100, 8)]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        return FilterExec(scan, [BinaryOp("gt", Column(1, "v"), Literal(0.0, T.FLOAT64))])

    reset_fusion_stats()
    tree = fuse_exec_tree(build(), ON)
    tree.collect()
    s1 = fusion_stats()
    assert s1["programs"] == 1
    assert s1["compiles"] == 2  # two distinct capacity buckets
    tree.collect()  # replay: same signatures, same buckets
    tree2 = fuse_exec_tree(build(), ON)  # same segment, fresh tree
    tree2.collect()
    s2 = fusion_stats()
    assert s2["compiles"] == s1["compiles"], "replay must not retrace"
    assert s2["compiles"] <= s2["programs"] * 2


def test_metric_attribution_splits_per_operator():
    """Fused-program time lands on the CONSTITUENT operators' metric
    nodes (top_ops must see FilterExec/ProjectExec, not one opaque
    stage), the span timeline receives the same nanos (the <=5%
    span/metric cross-check relies on it), and the residual stage
    overhead NOT covered by the per-constituent split lands on the STAGE
    node — metric conservation: program splits + stage residual ==
    measured stage wall, exactly (a stage that reports 0.0 in top_ops
    while carrying fused_batches was dropping its residual)."""
    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.metrics import MetricNode

    batches = [_frame(2000, 9)]

    def build():
        scan = MemoryScanExec([list(batches)], batches[0].schema)
        f = FilterExec(scan, [BinaryOp("gt", Column(1, "v"), Literal(0.0, T.FLOAT64))])
        return ProjectExec(f, [BinaryOp("add", Column(0, "k"), Literal(1, T.INT64))], ["k1"])

    tree = fuse_exec_tree(build(), ON)
    ctx = ExecutionContext()
    ctx.metrics.name = tree.name
    list(tree.execute(0, ctx))
    per_op: dict = {}
    MetricNode.accumulate_op_totals(ctx.metrics.snapshot(), per_op)
    assert "FilterExec" in per_op and "ProjectExec" in per_op
    total = per_op["FilterExec"].get("elapsed_compute", 0) + \
        per_op["ProjectExec"].get("elapsed_compute", 0)
    assert total > 0
    stage = per_op["FusedStageExec"]
    assert stage.get("fused_batches") == 1
    # conservation: sum of per-constituent splits + the stage's residual
    # equals the measured wall nanos of the stage's per-batch work
    assert stage.get("elapsed_compute", 0) > 0
    assert total + stage["elapsed_compute"] == stage["stage_wall"]


# ---------------------------------------------------------------------------
# probe-prologue & writer-repartition stage extensions (ISSUE 10)
# ---------------------------------------------------------------------------


def _probe_frame(seed, n=6000, jump=False):
    """Probe side with NULL keys; ``jump`` flips selectivity ~0 -> ~50%
    mid-stream so the compaction predictor under-sizes a bucket (forced
    mispredict repair)."""
    rng = np.random.default_rng(seed)
    k = rng.integers(1, 200, n).astype(object)
    if jump:
        k[: n // 3] = 10_000  # out of the build's key range: no matches
    probe = pd.DataFrame({"k": k, "v": rng.normal(size=n)})
    probe.loc[probe.index % 7 == 0, "k"] = None  # NULL keys never join
    schema = T.Schema((T.Field("k", T.INT64, True), T.Field("v", T.FLOAT64, True)))
    return [
        Batch.from_pydict(
            {"k": probe.k.iloc[i:i + 1000].tolist(),
             "v": probe.v.iloc[i:i + 1000].tolist()}, schema)
        for i in range(0, n, 1000)
    ]


def _assert_rows_equal(a, b):
    assert len(a) == len(b)
    cols = list(a.columns)
    a = a.sort_values(cols, na_position="first").reset_index(drop=True)
    b = b.sort_values(cols, na_position="first").reset_index(drop=True)
    for c in cols:
        assert ((a[c].isna() & b[c].isna()) | (a[c] == b[c])).all(), c


@pytest.mark.parametrize("jump", [False, True], ids=["steady", "mispredict"])
@pytest.mark.parametrize(
    "join_type", ["inner", "left", "left_semi", "left_anti", "existence"]
)
def test_probe_prologue_bit_identity(join_type, jump):
    """The fused probe prologue (key eval + canon + unique lookup +
    gather/compact-take inside ONE stage program) is bit-identical to the
    eager per-op jit chain across join types, through the predicted-
    compaction window and its forced-mispredict repair."""
    dim = pd.DataFrame({"id": np.arange(1, 101, dtype=np.int64),
                        "b": np.arange(1, 101) * 2.0})
    dim_b = [Batch.from_pandas(dim)]

    def build():
        pb = _probe_frame(3, jump=jump)
        scan = MemoryScanExec([pb], pb[0].schema)
        flt = FilterExec(scan, [BinaryOp(
            "gt", Column(1, "v"), Literal(-10.0, T.FLOAT64))])
        return BroadcastHashJoinExec(
            flt, MemoryScanExec([list(dim_b)], dim_b[0].schema),
            [Column(0, "k")], [Column(0, "id")], join_type,
            build_side="right",
        )

    from auron_tpu.exec.base import ExecutionContext

    eager = build().collect().to_pandas()
    reset_fusion_stats()
    tree = fuse_exec_tree(build(), ON)
    ctx = ExecutionContext()
    ctx.metrics.name = tree.name
    out = list(tree.execute(0, ctx))
    fused = (
        pd.concat([b.to_pandas() for b in out], ignore_index=True)
        if out else eager.iloc[:0]
    )
    _assert_rows_equal(eager, fused)
    st = fusion_stats()
    assert st["probe_segments"] >= 1
    # teeth: the stage program actually dispatched (a silent publish
    # failure would pass bit-identity via the eager fallback)
    assert ctx.metrics.total("fused_batches") > 0
    if jump and join_type == "inner":
        # the selectivity jump must exercise the repair protocol
        assert ctx.metrics.total("sel_mispredicts") > 0


def test_probe_prologue_exists_lut_bit_identity():
    """Duplicate-keyed build probed by semi/anti: the existence-LUT probe
    rides the stage program (payload kind "exists")."""
    dup = pd.DataFrame({"id": np.tile(np.arange(1, 51, dtype=np.int64), 3),
                        "b": np.arange(150) * 1.0})
    dim_b = [Batch.from_pandas(dup)]

    for join_type in ("left_semi", "left_anti"):
        def build():
            pb = _probe_frame(5)
            scan = MemoryScanExec([pb], pb[0].schema)
            flt = FilterExec(scan, [BinaryOp(
                "gt", Column(1, "v"), Literal(-10.0, T.FLOAT64))])
            return BroadcastHashJoinExec(
                flt, MemoryScanExec([list(dim_b)], dim_b[0].schema),
                [Column(0, "k")], [Column(0, "id")], join_type,
                build_side="right",
            )

        from auron_tpu.exec.base import ExecutionContext

        eager = build().collect().to_pandas()
        reset_fusion_stats()
        tree = fuse_exec_tree(build(), ON)
        ctx = ExecutionContext()
        ctx.metrics.name = tree.name
        out = list(tree.execute(0, ctx))
        fused = pd.concat([b.to_pandas() for b in out], ignore_index=True)
        _assert_rows_equal(eager, fused)
        assert fusion_stats()["probe_segments"] >= 1
        assert ctx.metrics.total("fused_batches") > 0, join_type


def test_fused_probe_deferred_agg_spill_midstream():
    """End-to-end q93 shape under memory pressure: fused probe prologue
    (LEFT join, null-heavy keys) feeding a bool-key partial aggregate on
    the DEFERRED count path, with a tiny MemManager budget forcing table
    spills mid-stream — fusion + deferral off/on agree row-exactly
    (counts bit-equal; float sums compared at 1e-9 — predictive
    compaction re-buckets the reduces, re-associating float adds the
    same way any merge-boundary shift does). The exactly-once staging
    contract through spill parks is the teeth here."""
    from auron_tpu.exec.agg_exec import AggExpr, HashAggExec
    from auron_tpu.memory.memmgr import MemManager

    dim = pd.DataFrame({"id": np.arange(1, 101, dtype=np.int64),
                        "b": np.arange(1, 101) * 2.0})
    dim_b = [Batch.from_pandas(dim)]

    def build():
        pb = _probe_frame(11, n=12000, jump=True)
        scan = MemoryScanExec([pb], pb[0].schema)
        j = BroadcastHashJoinExec(
            scan, MemoryScanExec([list(dim_b)], dim_b[0].schema),
            [Column(0, "k")], [Column(0, "id")], "left", build_side="right",
        )
        p = HashAggExec(
            j, [(IsNull(Column(0, "k")), "k_null")],
            [(AggExpr("count_star", None), "rows"),
             (AggExpr("sum", Column(1, "v")), "s")], "partial")
        return HashAggExec(
            p, [(Column(0, "k_null"), "k_null")],
            [(AggExpr("count_star", None), "rows"),
             (AggExpr("sum", Column(1, "s")), "s")], "final")

    from auron_tpu.utils.config import AGG_PARTIAL_DEFER, active_conf

    conf = active_conf()
    saved = conf.get(AGG_PARTIAL_DEFER)
    MemManager.init(budget_bytes=64 << 10)  # forces mid-stream spills
    try:
        conf.set(AGG_PARTIAL_DEFER, "off")
        eager = build().collect().to_pandas()
        conf.set(AGG_PARTIAL_DEFER, "on")
        fused = fuse_exec_tree(build(), ON).collect().to_pandas()
    finally:
        conf.set(AGG_PARTIAL_DEFER, saved)
        MemManager.init()
    eager = eager.sort_values("k_null").reset_index(drop=True)
    fused = fused.sort_values("k_null").reset_index(drop=True)
    assert eager["k_null"].tolist() == fused["k_null"].tolist()
    assert eager["rows"].tolist() == fused["rows"].tolist()  # exactly-once
    for a, b in zip(eager["s"], fused["s"]):
        assert a == pytest.approx(b, rel=1e-9)


def test_writer_stage_counted_and_byte_identical(tmp_path):
    """Fused repartition (pids + clustering inside the stage program)
    produces byte-identical shuffle files to the eager writer, for hash
    and round-robin partitionings."""
    import os

    from auron_tpu.exec.base import ExecutionContext
    from auron_tpu.exec.shuffle.partitioning import (
        HashPartitioning, RoundRobinPartitioning,
    )
    from auron_tpu.exec.shuffle.writer import ShuffleWriterExec

    frames = [_frame(2000, s) for s in (1, 2, 3)]

    def run(conf, part, d):
        scan = MemoryScanExec([list(frames)], frames[0].schema)
        prj = ProjectExec(scan, [Column(0, "k"), Column(1, "v")], ["k", "v"])
        w = ShuffleWriterExec(prj, part, str(d / "x.data"), str(d / "x.index"))
        tree = fuse_exec_tree(w, conf)
        list(tree.execute(0, ExecutionContext()))
        return (d / "x.data").read_bytes(), (d / "x.index").read_bytes()

    from auron_tpu.utils.config import Configuration

    OFF = Configuration({"exec.fuse.enable": "off"})
    for name, mk in (("hash", lambda: HashPartitioning([Column(0, "k")], 3)),
                     ("rr", lambda: RoundRobinPartitioning(3))):
        d_on = tmp_path / f"{name}_on"
        d_off = tmp_path / f"{name}_off"
        d_on.mkdir(), d_off.mkdir()
        reset_fusion_stats()
        on_data, on_idx = run(ON, mk(), d_on)
        assert fusion_stats()["writer_segments"] >= 1, name
        off_data, off_idx = run(OFF, mk(), d_off)
        # the trailing 16 bytes carry a random attempt pair tag
        assert on_data[:-16] == off_data[:-16], name
        assert len(on_idx) == len(off_idx), name

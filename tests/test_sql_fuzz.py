"""Grammar-directed SQL parser fuzz + diagnostic teeth.

Round-trip property (pins the parser AND the canonical renderer): a
grammar-directed generator builds random ASTs over the supported subset,
renders them with ``sqlast.to_sql``, and the parse of the rendering must
equal the original node-for-node (positions are excluded from dataclass
equality). The corpus texts get the same treatment:
``parse(to_sql(parse(q))) == parse(q)`` for every gate query.

Diagnostic teeth: >= 10 out-of-subset constructs each raise a
positioned ``SqlUnsupported`` naming the construct — including one test
that pins the position to the exact line the construct sits on.
"""

import random

import pytest

from auron_tpu.models import sqlgate
from auron_tpu.sql import SqlUnsupported, compile_text, parse, tpcds_catalog
from auron_tpu.sql import sqlast as A

# ---------------------------------------------------------------------------
# grammar-directed generator
# ---------------------------------------------------------------------------

_COLS = ["c0", "c1", "c2", "c3", "qty", "price", "d_year"]
_TABLES = ["t0", "t1", "store_sales", "date_dim"]
_FUNCS = ["sum", "avg", "min", "max", "count", "substr", "coalesce"]
_CMP = ["=", "<>", "<", "<=", ">", ">="]
_ARITH = ["+", "-", "*", "/"]


class Gen:
    def __init__(self, seed: int):
        self.r = random.Random(seed)
        self.n_alias = 0

    def alias(self) -> str:
        self.n_alias += 1
        return f"a{self.n_alias}"

    # -- expressions --------------------------------------------------------

    def scalar(self, depth: int) -> A.Expr:
        r = self.r
        if depth <= 0:
            return r.choice([
                A.Ident((r.choice(_COLS),)),
                A.Ident((r.choice(_TABLES), r.choice(_COLS))),
                A.NumberLit(str(r.randint(0, 999))),
                A.NumberLit(f"{r.randint(0, 99)}.{r.randint(0, 99):02d}"),
                A.StringLit(r.choice(["x", "it's", "Home", ""])),
                A.DateLit("2000-0%d-15" % r.randint(1, 9)),
                A.NullLit(),
            ])
        pick = r.randrange(6)
        if pick == 0:
            return A.BinOp(r.choice(_ARITH),
                           self.scalar(depth - 1), self.scalar(depth - 1))
        if pick == 1:
            name = r.choice(_FUNCS)
            if name == "count" and r.random() < 0.5:
                return A.FuncCall(name, star=True)
            return A.FuncCall(name, (self.scalar(depth - 1),))
        if pick == 2:
            whens = tuple(
                (self.pred(depth - 1), self.scalar(depth - 1))
                for _ in range(r.randint(1, 2)))
            orelse = self.scalar(depth - 1) if r.random() < 0.7 else None
            return A.CaseExpr(None, whens, orelse)
        if pick == 3:
            operand = self.scalar(0)
            whens = tuple(
                (self.scalar(0), self.scalar(depth - 1))
                for _ in range(r.randint(1, 2)))
            return A.CaseExpr(operand, whens, self.scalar(0))
        if pick == 4:
            to = r.choice([A.TypeName("integer"), A.TypeName("decimal", (7, 2)),
                           A.TypeName("double")])
            return A.Cast(self.scalar(depth - 1), to)
        return A.UnaryOp("-", A.Ident((r.choice(_COLS),)))

    def pred(self, depth: int) -> A.Expr:
        r = self.r
        if depth <= 0:
            return A.BinOp(r.choice(_CMP), self.scalar(0), self.scalar(0))
        pick = r.randrange(8)
        if pick == 0:
            return A.BinOp(r.choice(["and", "or"]),
                           self.pred(depth - 1), self.pred(depth - 1))
        if pick == 1:
            return A.UnaryOp("not", self.pred(depth - 1))
        if pick == 2:
            return A.Between(self.scalar(0), self.scalar(0), self.scalar(0),
                             negated=r.random() < 0.3)
        if pick == 3:
            items = tuple(A.NumberLit(str(r.randint(0, 9)))
                          for _ in range(r.randint(1, 4)))
            return A.InList(self.scalar(0), items, negated=r.random() < 0.3)
        if pick == 4:
            return A.LikePred(A.Ident((r.choice(_COLS),)),
                              r.choice(["ab%", "%x%", "_n"]),
                              negated=r.random() < 0.3)
        if pick == 5:
            return A.IsNullPred(self.scalar(0), negated=r.random() < 0.5)
        if pick == 6:
            return A.InSubquery(self.scalar(0), self.query(0),
                                negated=r.random() < 0.3)
        return A.BinOp(r.choice(_CMP), self.scalar(depth - 1), self.scalar(0))

    # -- relations ----------------------------------------------------------

    def table_ref(self, depth: int) -> A.TableRef:
        r = self.r
        if depth <= 0 or r.random() < 0.5:
            alias = self.alias() if r.random() < 0.5 else None
            return A.TableName(r.choice(_TABLES), alias)
        if r.random() < 0.3:
            return A.DerivedTable(self.query(0), self.alias())
        on = A.BinOp("=", A.Ident((r.choice(_COLS),)),
                     A.Ident((r.choice(_COLS),)))
        return A.Join(self.table_ref(depth - 1), self.table_ref(0),
                      r.choice(["inner", "left"]), on)

    # -- statements ---------------------------------------------------------

    def select(self, depth: int) -> A.Select:
        r = self.r
        items = tuple(
            A.SelectItem(self.scalar(depth),
                         self.alias() if r.random() < 0.6 else None)
            for _ in range(r.randint(1, 4)))
        from_ = tuple(self.table_ref(depth)
                      for _ in range(r.randint(1, 2)))
        where = self.pred(depth) if r.random() < 0.8 else None
        group_by = tuple(A.Ident((r.choice(_COLS),))
                         for _ in range(r.randint(0, 2)))
        having = self.pred(0) if group_by and r.random() < 0.4 else None
        return A.Select(items, from_, where, group_by, having,
                        distinct=r.random() < 0.2)

    def query(self, depth: int) -> A.Query:
        r = self.r
        body: A.Select | A.UnionAll = self.select(depth)
        if depth > 0 and r.random() < 0.2:
            body = A.UnionAll((body, self.select(depth - 1)))
        ctes = tuple(
            A.Cte(f"cte{i}", self.select(max(depth - 1, 0)))
            for i in range(r.randint(0, 2) if depth > 0 else 0))
        order = tuple(
            A.OrderItem(A.Ident((r.choice(_COLS),)), asc=r.random() < 0.7,
                        nulls_first=r.choice([None, True, False]))
        for _ in range(r.randint(0, 2)))
        limit = r.choice([None, 10, 100]) if order else None
        return A.Query(body, ctes, order, limit)


@pytest.mark.parametrize("seed", range(40))
def test_generated_ast_roundtrips(seed):
    g = Gen(seed)
    ast = g.query(depth=3)
    text = A.to_sql(ast)
    reparsed = parse(text)
    assert reparsed == ast, text
    # and the rendering is a fixpoint: render(parse(render)) == render
    assert A.to_sql(reparsed) == text


def test_corpus_texts_roundtrip():
    for case in sqlgate.CASES:
        ast = parse(case.sql)
        again = parse(A.to_sql(ast))
        assert again == ast, case.name


# ---------------------------------------------------------------------------
# diagnostic teeth: out-of-subset constructs raise positioned SqlUnsupported
# ---------------------------------------------------------------------------

_CATALOG = tpcds_catalog()

UNSUPPORTED_SNIPPETS = [
    # (construct name, sql)
    ("select *", "select * from store_sales"),
    ("window function",
     "select sum(ss_quantity) over (partition by ss_store_sk) w"
     " from store_sales"),
    ("rollup",
     "select d_year, sum(ss_quantity) s from store_sales, date_dim"
     " where ss_sold_date_sk = d_date_sk group by rollup(d_year)"),
    ("cube",
     "select d_year, count(*) c from date_dim group by cube(d_year)"),
    ("intersect",
     "select d_year from date_dim intersect select d_year from date_dim"),
    ("except",
     "select d_year from date_dim except select d_year from date_dim"),
    ("union distinct",
     "select d_year from date_dim union select d_year from date_dim"),
    ("right outer join",
     "select d_year from store_sales right join date_dim"
     " on ss_sold_date_sk = d_date_sk"),
    ("full outer join",
     "select d_year from store_sales full outer join date_dim"
     " on ss_sold_date_sk = d_date_sk"),
    ("cross join",
     "select d_year from store_sales cross join date_dim"),
    ("natural join",
     "select d_year from store_sales natural join date_dim"),
    ("join using",
     "select d_year from store_sales join date_dim using (d_date_sk)"),
    ("exists subquery",
     "select d_year from date_dim where exists"
     " (select d_date_sk from date_dim)"),
    ("scalar subquery",
     "select d_year from date_dim"
     " where d_year > (select avg(d_year) from date_dim)"),
    ("string concatenation ||",
     "select d_day_name || 'x' s from date_dim"),
    ("interval unit month",
     "select d_date + interval '3' month s from date_dim"),
    ("distinct aggregate",
     "select count(distinct d_year) c from date_dim"),
    ("having without group by",
     "select d_year from date_dim having d_year > 5"),
    ("non-exact IN list item",
     "select d_year from date_dim where d_year in (5, 2.5)"),
    ("integer literal out of range for int32",
     "select d_year from date_dim where d_year in (3000000000)"),
    # the constant FOLD must range-check too — a wrapped fold would make
    # `d_year = -2` rows match this predicate
    ("integer literal out of range for int32",
     "select d_year from date_dim where d_year = 2147483647 + 2147483647"),
]


@pytest.mark.parametrize(
    "construct,sql", UNSUPPORTED_SNIPPETS,
    ids=[c for c, _ in UNSUPPORTED_SNIPPETS])
def test_unsupported_construct_diagnosed(construct, sql):
    with pytest.raises(SqlUnsupported) as ei:
        compile_text(sql, _CATALOG)
    e = ei.value
    assert e.construct == construct
    assert e.pos.line >= 1 and e.pos.col >= 1, "diagnostic must be positioned"


def test_diagnostic_position_points_at_the_construct():
    sql = ("select d_year\n"
           "from date_dim\n"
           "cross join store_sales")
    with pytest.raises(SqlUnsupported) as ei:
        compile_text(sql, _CATALOG)
    assert ei.value.pos.line == 3
    assert ei.value.construct == "cross join"
    # the rendered message carries line:col and a caret snippet
    msg = str(ei.value)
    assert "3:" in msg and "^" in msg


def test_never_a_wrong_plan_for_half_understood_sql():
    """The failure contract: every UNSUPPORTED snippet either raises a
    diagnostic or is absent from the corpus — compile_text can never
    return a LoweredQuery for them (checked by the raises above), and
    syntax garbage raises SqlSyntaxError, not a plan."""
    from auron_tpu.sql import SqlSyntaxError

    with pytest.raises(SqlSyntaxError):
        compile_text("select from where", _CATALOG)
    with pytest.raises(SqlSyntaxError):
        compile_text("frobnicate the table", _CATALOG)

"""Unit coverage for the sync-free pipeline pieces: the selectivity
predictor (exec/selectivity.py) and the async transfer window
(runtime/transfer.py)."""

import jax.numpy as jnp

from auron_tpu.columnar.batch import compaction_bucket
from auron_tpu.exec.selectivity import SelectivityPredictor, predictor_enabled
from auron_tpu.runtime.transfer import TransferWindow, harvest
from auron_tpu.utils.config import (
    Configuration,
    JOIN_COMPACT_OUTPUT,
    SELECTIVITY_EWMA_ALPHA,
    SELECTIVITY_HEADROOM,
    SELECTIVITY_PREDICTOR_ENABLE,
    SELECTIVITY_SHRINK_PATIENCE,
)


def _conf(**kv):
    c = Configuration()
    for k, v in kv.items():
        c.set(k, v)
    return c


def test_compaction_bucket_policy():
    # the one shared dense-vs-compact threshold (chain + driver + predictor)
    assert compaction_bucket(100, 1024) == 128
    assert compaction_bucket(0, 1024) == 128       # clamp to min bucket
    assert compaction_bucket(200, 1024) == 256
    assert compaction_bucket(300, 1024) is None    # 512*4 > 1024: dense
    assert compaction_bucket(100, 128) is None     # tiny batch: dense


def test_predictor_seeds_then_predicts_and_grows_immediately():
    p = SelectivityPredictor(_conf())
    assert p.predict(1 << 20) is None              # no history: seed path
    p.observe(100)
    b1 = p.predict(1 << 20)
    assert b1 is not None and b1 >= 128
    # overflow -> immediate growth (never two repairs for one regime shift)
    p.observe(50_000, predicted=b1)
    assert p.mispredicts == 1
    assert p.predict(1 << 20) >= 50_000


def test_predictor_shrinks_only_after_patience():
    c = _conf(**{SELECTIVITY_SHRINK_PATIENCE.key: 3,
                 SELECTIVITY_EWMA_ALPHA.key: 1.0,
                 SELECTIVITY_HEADROOM.key: 1.0})
    p = SelectivityPredictor(c)
    p.observe(10_000)
    big = p.predict(1 << 20)
    p.observe(10)   # 1 low batch
    assert p.predict(1 << 20) == big
    p.observe(10)   # 2
    assert p.predict(1 << 20) == big
    p.observe(10)   # 3 -> shrink
    assert p.predict(1 << 20) < big


def test_predictor_clamped_to_input_capacity():
    p = SelectivityPredictor(_conf())
    p.observe(1 << 20)
    assert p.predict(1024) <= 1024


def test_predictor_enabled_knob_follows_compaction():
    on = _conf(**{SELECTIVITY_PREDICTOR_ENABLE.key: "on"})
    off = _conf(**{SELECTIVITY_PREDICTOR_ENABLE.key: "off"})
    auto_off = _conf(**{JOIN_COMPACT_OUTPUT.key: "off"})
    assert predictor_enabled(on)
    assert not predictor_enabled(off)
    assert not predictor_enabled(auto_off)


def test_transfer_window_fifo_and_depth():
    w = TransferWindow(2)
    got = []
    for i in range(6):
        got += w.push((jnp.int32(i),), f"p{i}")
    # depth 2: pushes 3..6 each evict the oldest
    assert [pl for _, pl in got] == ["p0", "p1", "p2", "p3"]
    got += list(w.drain())
    assert [pl for _, pl in got] == [f"p{i}" for i in range(6)]
    assert [int(r[0]) for r, _ in got] == list(range(6))
    assert len(w) == 0


def test_transfer_window_empty_arrays_and_harvest():
    w = TransferWindow(1)
    out = w.push((), "a") + w.push((), "b")
    assert [pl for _, pl in out] == ["a"]
    (v,) = harvest(jnp.arange(3))
    assert list(v) == [0, 1, 2]


def test_predictor_enabled_auto_follows_compaction_auto():
    """The predictor's auto arm resolves through the compaction knob's
    OWN tri-state (resolve_tri composition, not a manual == chain): with
    both knobs at auto on the CPU backend, compaction is on, so the
    predictor is too; forcing compaction on keeps it on."""
    assert predictor_enabled(_conf())  # both auto -> CPU -> on
    assert predictor_enabled(_conf(**{JOIN_COMPACT_OUTPUT.key: "on"}))

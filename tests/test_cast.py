"""Cast long tail (cast.rs parity): lenient string->datetime, X->string
Java formatting, nested list/map/struct casts.

Spark oracle values in comments were produced by spark-shell 3.5:
  spark.sql("select cast(X as Y)").
"""

import datetime as dt
import decimal as pydec

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import types as T
from auron_tpu.columnar import Batch
from auron_tpu.exprs import cast as C
from auron_tpu.exprs import eval_exprs
from auron_tpu.exprs.ir import Cast, col


def _run(data, exprs, schema=None):
    b = Batch.from_pydict(data, schema=schema)
    outs = eval_exprs(b, exprs)
    n = b.num_rows()
    res = []
    for o in outs:
        vals = np.asarray(o.values)[:n]
        mask = np.asarray(o.validity)[:n]
        if o.dtype.is_dict_encoded:
            d = o.dict.to_pylist()
            res.append([d[v] if m else None for v, m in zip(vals, mask)])
        else:
            res.append([v if m else None for v, m in zip(vals.tolist(), mask)])
    return res


# ---------------------------------------------------------------------------
# lenient string -> date
# ---------------------------------------------------------------------------


def _days(y, m, d):
    return (dt.date(y, m, d) - dt.date(1970, 1, 1)).days


@pytest.mark.parametrize(
    "s,expect",
    [
        ("2021-03-05", _days(2021, 3, 5)),
        ("2021-3-5", _days(2021, 3, 5)),  # 1-digit segments
        ("2021-03", _days(2021, 3, 1)),  # day defaults to 1
        ("2021", _days(2021, 1, 1)),
        (" 2021-01-01 ", _days(2021, 1, 1)),  # trimmed
        ("2021-01-01T12:33:00", _days(2021, 1, 1)),  # time ignored
        ("2021-01-01 whatever", _days(2021, 1, 1)),  # junk after sep ignored
        ("02021-01-01", _days(2021, 1, 1)),  # 5-digit year ok (<=7)
        ("21-01-01", None),  # 2-digit year invalid
        ("2021-13-01", None),
        ("2021-02-30", None),
        ("2021/01/01", None),
        ("", None),
        ("abc", None),
    ],
)
def test_string_to_date_lenient(s, expect):
    assert C.spark_string_to_date(s) == expect


# ---------------------------------------------------------------------------
# lenient string -> timestamp
# ---------------------------------------------------------------------------


def _us(y, mo, d, h=0, mi=0, s=0, us=0):
    base = dt.datetime(y, mo, d, h, mi, s, tzinfo=dt.timezone.utc)
    return int(base.timestamp()) * 1_000_000 + us


@pytest.mark.parametrize(
    "s,expect",
    [
        ("2019-10-06 10:11:12", _us(2019, 10, 6, 10, 11, 12)),
        ("2019-10-06T10:11:12", _us(2019, 10, 6, 10, 11, 12)),
        ("2019-10-06 10:11", _us(2019, 10, 6, 10, 11)),
        ("2019-10-06 10", _us(2019, 10, 6, 10)),  # hour-only time
        ("2019-10-06", _us(2019, 10, 6)),
        ("2019-10", _us(2019, 10, 1)),
        ("2019", _us(2019, 1, 1)),
        ("2019-10-06 10:11:12.345678", _us(2019, 10, 6, 10, 11, 12, 345678)),
        # 9 fraction digits truncate to micros
        ("2019-10-06 10:11:12.123456789", _us(2019, 10, 6, 10, 11, 12, 123456)),
        ("2019-10-06 10:11:12.5", _us(2019, 10, 6, 10, 11, 12, 500000)),
        # zones
        ("2019-10-06 10:11:12Z", _us(2019, 10, 6, 10, 11, 12)),
        ("2019-10-06 10:11:12 UTC", _us(2019, 10, 6, 10, 11, 12)),
        ("2019-10-06 10:11:12+08:00", _us(2019, 10, 6, 2, 11, 12)),
        ("2019-10-06 10:11:12-0130", _us(2019, 10, 6, 11, 41, 12)),
        ("2019-10-06 10:11:12+8", _us(2019, 10, 6, 2, 11, 12)),
        ("2019-10-06 10:11:12GMT+01:00", _us(2019, 10, 6, 9, 11, 12)),
        # invalids
        ("2019-10-06 25:00:00", None),
        ("2019-10-06 10:61:00", None),
        ("2019-10-06 10:11:12.1234567890", None),  # >9 fraction digits
        ("2019-10-06 10:11:12 NOTAZONE", None),
        ("1", None),  # 1-digit year
        ("", None),
    ],
)
def test_string_to_timestamp_lenient(s, expect):
    assert C.spark_string_to_timestamp(s) == expect


def test_string_to_timestamp_fraction_requires_seconds():
    assert C.spark_string_to_timestamp("2019-10-06 10:11.5") is None


def test_bare_time_uses_default_date():
    got = C.spark_string_to_timestamp("12:30:45", default_date=dt.date(2020, 5, 4))
    assert got == _us(2020, 5, 4, 12, 30, 45)


def test_bare_time_with_leading_t_separator():
    """Spark accepts 'T12:34:56' (empty date part before the separator);
    the leading T must not be misread as a zone id (ADVICE r3)."""
    got = C.spark_string_to_timestamp("T12:34:56", default_date=dt.date(2020, 5, 4))
    assert got == _us(2020, 5, 4, 12, 34, 56)
    assert C.spark_string_to_timestamp("T9:05", default_date=dt.date(2020, 5, 4)) \
        == _us(2020, 5, 4, 9, 5, 0)
    # a bare separator (or separator + zone) has no time body: still null
    assert C.spark_string_to_timestamp("T") is None
    assert C.spark_string_to_timestamp("TZ") is None
    assert C.spark_string_to_timestamp("T+01:00") is None


def test_region_zone_if_zoneinfo_available():
    got = C.spark_string_to_timestamp("2019-01-15 12:00:00 America/New_York")
    if got is not None:  # zoneinfo db present
        assert got == _us(2019, 1, 15, 17, 0, 0)  # EST = UTC-5 in January


# ---------------------------------------------------------------------------
# Java Float/Double.toString
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "x,expect",
    [
        (1.0, "1.0"),
        (-1.5, "-1.5"),
        (0.0, "0.0"),
        (10000000.0, "1.0E7"),  # >= 1e7 goes scientific
        (9999999.5, "9999999.5"),
        (0.001, "0.001"),
        (0.0001, "1.0E-4"),  # < 1e-3 goes scientific
        (123456.789, "123456.789"),
        (1e100, "1.0E100"),
        (-2.5e-9, "-2.5E-9"),
        (float("nan"), "NaN"),
        (float("inf"), "Infinity"),
        (float("-inf"), "-Infinity"),
    ],
)
def test_java_double_str(x, expect):
    assert C._java_fp_str(x, single=False) == expect


def test_java_float_str_shortest_for_float32():
    # 0.1f prints as 0.1 (shortest for float precision), not 0.100000001...
    assert C._java_fp_str(0.1, single=True) == "0.1"
    assert C._java_fp_str(float(np.float32(1.0) / 3), single=True) == "0.33333334"


def test_negative_zero():
    assert C._java_fp_str(-0.0, single=False) == "-0.0"


# ---------------------------------------------------------------------------
# Java BigDecimal.toString
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "unscaled,scale,expect",
    [
        (12345, 2, "123.45"),
        (-12345, 2, "-123.45"),
        (12345, 0, "12345"),
        (5, 7, "5E-7"),  # adjusted exponent < -6 -> scientific
        (50, 7, "0.0000050"),  # adjusted exponent == -6 -> plain
        (123, 7, "0.0000123"),  # adjusted exponent -5 >= -6 -> plain
        (12, 9, "1.2E-8"),
        (0, 2, "0.00"),
        (7, 3, "0.007"),
    ],
)
def test_java_bigdecimal_str(unscaled, scale, expect):
    assert C._java_bigdecimal_str(unscaled, scale) == expect


# ---------------------------------------------------------------------------
# timestamp/date -> string
# ---------------------------------------------------------------------------


def test_timestamp_to_string_trims_fraction():
    us = _us(2019, 10, 6, 10, 11, 12)
    assert C._timestamp_str(us) == "2019-10-06 10:11:12"
    assert C._timestamp_str(us + 500000) == "2019-10-06 10:11:12.5"
    assert C._timestamp_str(us + 123450) == "2019-10-06 10:11:12.12345"


# ---------------------------------------------------------------------------
# column casts through the evaluator
# ---------------------------------------------------------------------------


def test_int_to_string_column():
    data = {"a": pa.array([1, None, -42, 1, 7], type=pa.int64())}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["1", None, "-42", "1", "7"]


def test_double_to_string_column():
    data = {"a": pa.array([1.5, 1e8, None], type=pa.float64())}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["1.5", "1.0E8", None]


def test_bool_and_date_to_string():
    data = {
        "b": pa.array([True, False, None]),
        "d": pa.array([dt.date(2021, 3, 5), dt.date(1969, 12, 31), None]),
    }
    bs, ds = _run(data, [Cast(col(0), T.STRING), Cast(col(1), T.STRING)])
    assert bs == ["true", "false", None]
    assert ds == ["2021-03-05", "1969-12-31", None]


def test_decimal_to_string_column():
    data = {"a": pa.array([pydec.Decimal("123.45"), pydec.Decimal("-0.07"), None],
                          type=pa.decimal128(10, 2))}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["123.45", "-0.07", None]


def test_string_to_timestamp_column_lenient():
    data = {"s": pa.array(["2019-10-06 10", "2019-10-06 10:11:12+08:00", "nope", None])}
    (out,) = _run(data, [Cast(col(0), T.TIMESTAMP)])
    assert out == [
        _us(2019, 10, 6, 10),
        _us(2019, 10, 6, 2, 11, 12),
        None,
        None,
    ]


def test_list_int_to_list_string():
    t = pa.list_(pa.int64())
    data = {"a": pa.array([[1, 2], [], None, [3, None]], type=t)}
    (out,) = _run(data, [Cast(col(0), T.DataType(T.TypeKind.LIST, inner=(T.STRING,)))])
    assert out == [["1", "2"], [], None, ["3", None]]


def test_list_string_to_list_int_invalid_elements_null():
    t = pa.list_(pa.string())
    data = {"a": pa.array([["1", "x", "3"]], type=t)}
    (out,) = _run(data, [Cast(col(0), T.DataType(T.TypeKind.LIST, inner=(T.INT64,)))])
    assert out == [[1, None, 3]]


def test_struct_cast_fields():
    t = pa.struct([("x", pa.int64()), ("y", pa.string())])
    data = {"a": pa.array([{"x": 1, "y": "2.5"}, {"x": None, "y": "bad"}], type=t)}
    dst = T.DataType(
        T.TypeKind.STRUCT, inner=(T.STRING, T.FLOAT64), struct_names=("x", "y")
    )
    (out,) = _run(data, [Cast(col(0), dst)])
    assert out == [{"x": "1", "y": 2.5}, {"x": None, "y": None}]


def test_map_cast_values():
    t = pa.map_(pa.string(), pa.int64())
    data = {"a": pa.array([[("k", 5)], []], type=t)}
    dst = T.DataType(T.TypeKind.MAP, inner=(T.STRING, T.STRING))
    (out,) = _run(data, [Cast(col(0), dst)])
    assert out == [[("k", "5")], []]


def test_list_to_string_display_format():
    t = pa.list_(pa.int64())
    data = {"a": pa.array([[1, 2, None]], type=t)}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["[1, 2, null]"]


def test_struct_to_string_display_format():
    t = pa.struct([("x", pa.int64()), ("y", pa.string())])
    data = {"a": pa.array([{"x": 1, "y": "a"}], type=t)}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["{1, a}"]


def test_map_to_string_display_format():
    t = pa.map_(pa.string(), pa.int64())
    data = {"a": pa.array([[("k", 1), ("j", None)]], type=t)}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["{k -> 1, j -> null}"]


def test_wide_decimal_to_string():
    big = pydec.Decimal("12345678901234567890.12")
    data = {"a": pa.array([big, None], type=pa.decimal128(25, 2))}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["12345678901234567890.12", None]


def test_string_to_wide_decimal_roundtrip():
    data = {"s": pa.array(["12345678901234567890.12", "oops"])}
    (out,) = _run(data, [Cast(col(0), T.decimal(25, 2))])
    assert out == [pydec.Decimal("12345678901234567890.12"), None]


def test_list_timestamp_to_string():
    # nested temporals arrive as datetime objects from the dictionary
    t = pa.list_(pa.timestamp("us"))
    data = {"a": pa.array([[dt.datetime(2019, 10, 6, 10, 11, 12)]], type=t)}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["[2019-10-06 10:11:12]"]


def test_list_date_cast_to_list_string():
    t = pa.list_(pa.date32())
    data = {"a": pa.array([[dt.date(2021, 3, 5), None]], type=t)}
    (out,) = _run(data, [Cast(col(0), T.DataType(T.TypeKind.LIST, inner=(T.STRING,)))])
    assert out == [["2021-03-05", None]]


def test_list_string_to_list_decimal_objects():
    t = pa.list_(pa.string())
    dst = T.DataType(T.TypeKind.LIST, inner=(T.decimal(10, 2),))
    data = {"a": pa.array([["1.25", "bad"]], type=t)}
    (out,) = _run(data, [Cast(col(0), dst)])
    assert out == [[pydec.Decimal("1.25"), None]]


def test_seven_digit_year_date():
    # python datetime caps at year 9999; Spark's LocalDate does not
    assert C.spark_string_to_date("123456-01-01") == C._days_from_civil(123456, 1, 1)
    assert C.spark_string_to_timestamp("123456-01-01 00:00:01") == (
        C._days_from_civil(123456, 1, 1) * 86400 + 1
    ) * 1_000_000


def test_days_from_civil_matches_datetime_in_range():
    for y, m, d in [(1970, 1, 1), (2000, 2, 29), (1969, 12, 31), (9999, 12, 31), (1, 1, 1)]:
        assert C._days_from_civil(y, m, d) == (dt.date(y, m, d) - dt.date(1970, 1, 1)).days


def test_int_to_binary_big_endian():
    data = {"a": pa.array([1, -1, None], type=pa.int32())}
    (out,) = _run(data, [Cast(col(0), T.BINARY)])
    assert out == [b"\x00\x00\x00\x01", b"\xff\xff\xff\xff", None]


def test_double_to_binary_not_castable():
    assert not C.can_cast(T.FLOAT64, T.BINARY)
    assert C.can_cast(T.INT64, T.BINARY)
    assert C.can_cast(T.STRING, T.BINARY)


def test_negative_zero_and_zero_distinct_in_string_cast():
    data = {"a": pa.array([0.0, -0.0, 0.0], type=pa.float64())}
    (out,) = _run(data, [Cast(col(0), T.STRING)])
    assert out == ["0.0", "-0.0", "0.0"]


def test_double_to_wide_decimal_exact():
    # regression: the scalar path must treat 2.5 as the VALUE, not unscaled
    data = {"a": pa.array([2.5, 1e20, None], type=pa.float64())}
    (out,) = _run(data, [Cast(col(0), T.decimal(38, 2))])
    assert out == [pydec.Decimal("2.50"), pydec.Decimal("1E+20").quantize(pydec.Decimal("0.01")), None]


def test_big_int_to_wide_decimal_no_spurious_null():
    v = 5_000_000_000_000_000_000  # > decimal(18) capacity, fits decimal(38)
    data = {"a": pa.array([v], type=pa.int64())}
    (out,) = _run(data, [Cast(col(0), T.decimal(38, 0))])
    assert out == [pydec.Decimal(v)]


def test_far_future_date_roundtrip_no_crash():
    # parser accepts 6-digit years; formatting must not hit datetime's cap
    days = C.spark_string_to_date("123456-01-02")
    assert C._date_str(days) == "123456-01-02"
    assert C._civil_from_days(C._days_from_civil(-44, 3, 15)) == (-44, 3, 15)


def test_lowercase_t_separator_rejected():
    assert C.spark_string_to_timestamp("2021-01-01t10:00:00") is None
    assert C.spark_string_to_timestamp("2021-01-01T10:00:00") is not None


def test_date_chop_ignores_zone_names_with_T():
    # 'T' inside a trailing zone name must not become the separator
    assert C.spark_string_to_date("2021-01-01 10:11:12 UTC") == _days(2021, 1, 1)
    assert C.spark_string_to_date("2021-01-01 10:11:12 EST") == _days(2021, 1, 1)


def test_cast_null_literal_to_string():
    from auron_tpu.exprs.ir import Literal

    data = {"a": pa.array([1, 2], type=pa.int64())}
    (out,) = _run(data, [Cast(Literal(None, T.NULL), T.STRING)])
    assert out == [None, None]


def test_can_cast_lattice():
    lst_i = T.DataType(T.TypeKind.LIST, inner=(T.INT64,))
    lst_s = T.DataType(T.TypeKind.LIST, inner=(T.STRING,))
    assert C.can_cast(lst_i, lst_s)
    assert C.can_cast(lst_i, T.STRING)
    assert not C.can_cast(lst_i, T.INT64)
    assert not C.can_cast(T.INT64, lst_i)
    assert C.can_cast(T.STRING, T.TIMESTAMP)
